"""tmoglint v3: SHD (SPMD/collective correctness) + ENV/EVT (contract
drift) rule tests.

Every rule gets known-bad fixtures (must be caught) and known-good
fixtures (must stay silent), the `fit_gbt_folds_sharded` subsample bar
is pinned at BOTH layers (lint-time SHD003 + the trace-time raise), and
the real repo's sharded modules are asserted clean — the acceptance
contract that the baseline stays EMPTY with the new families on.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tools.tmoglint.core import LintContext, run_rules, scan_paths

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHARD_PRELUDE = textwrap.dedent("""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
""")


def shard_src(body: str) -> str:
    """Prelude + dedented fixture body (dedent cannot handle the two
    indentation levels once concatenated)."""
    return SHARD_PRELUDE + textwrap.dedent(body)


def lint(src: str, path: str = "pkg/mod.py", rules=None):
    ctx = LintContext(path, textwrap.dedent(src))
    return run_rules([ctx], only=rules)


def lint_many(named_srcs, rules=None):
    ctxs = [LintContext(p, textwrap.dedent(s)) for p, s in named_srcs]
    return run_rules(ctxs, only=rules)


def lint_tree(tmp_path, files, paths=("."), rules=None):
    """Write `files` under tmp_path and lint via scan_paths so ctxs
    carry a real lint root (the ENV/EVT doc checks need one)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    ctxs, errors = scan_paths(list(paths), str(tmp_path))
    return errors + run_rules(ctxs, only=rules)


def rules_of(findings):
    return sorted(f.rule for f in findings)


# -- SHD001: unreduced cross-shard output ------------------------------------

class TestSHD001:
    def test_forgot_the_psum(self):
        """The motivating bug: replicated out_spec, body never reduces —
        correct at 1 device, silently wrong at N>1."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return x.sum(axis=0)
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD001"])
        assert len(out) == 1 and out[0].rule == "SHD001"
        assert "psum" in out[0].message

    def test_one_of_two_outputs_unreduced(self):
        """Tuple out_specs: the reduced output passes, the forgotten
        one flags — findings are per-position."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    hist = x.sum(axis=0)
                    merged = jax.lax.psum(hist, "batch")
                    return merged, hist
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=(P(), P()))
        """), rules=["SHD001"])
        assert len(out) == 1
        assert "output 1" in out[0].message

    def test_negative_psum_through_threaded_helper(self):
        """The repo idiom: an `_allreduce(v, axis_name)` helper with the
        axis threaded through a kwarg — the reduction is seen
        interprocedurally."""
        out = lint(shard_src("""
            def _allreduce(v, axis_name):
                return jax.lax.psum(v, axis_name) \\
                    if axis_name is not None else v

            def _impl(x, axis_name=None):
                acc = x.sum(axis=0)
                return _allreduce(acc, axis_name)

            def build(mesh):
                def core(x):
                    return _impl(x, axis_name="batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD001"])
        assert out == []

    def test_negative_sharded_out_spec_needs_no_reduction(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return x * 2.0
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P("batch", None))
        """), rules=["SHD001"])
        assert out == []

    def test_negative_scan_carry_accumulator_psummed(self):
        """lax.scan-accumulated partial sums + one psum at the end: the
        stats-engine shape."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    def body(acc, row):
                        return acc + row, None
                    acc0 = jnp.zeros(x.shape[1])
                    acc, _ = jax.lax.scan(body, acc0, x)
                    return jax.lax.psum(acc, "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD001"])
        assert out == []

    def test_scan_carry_without_psum_flags(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    def body(acc, row):
                        return acc + row, None
                    acc0 = jnp.zeros(x.shape[1])
                    acc, _ = jax.lax.scan(body, acc0, x)
                    return acc
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD001"])
        assert len(out) == 1

    def test_suppression_with_justification(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return x.sum(axis=0)
                return shard_map(
                    core, mesh, in_specs=(P("batch", None),),
                    # tmoglint: disable=SHD001  single-device by design
                    out_specs=P())
        """), rules=["SHD001"])
        assert out == []


# -- SHD002: axis mismatch / unbound axis ------------------------------------

class TestSHD002:
    def test_axis_name_mismatch(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return jax.lax.psum(x.sum(axis=0), "data")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD002"])
        assert len(out) == 1
        assert "'data'" in out[0].message and "batch" in out[0].message

    def test_unbound_axis_outside_shard_map(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                return jax.lax.psum(x, "batch")
        """, rules=["SHD002"])
        assert len(out) == 1
        assert "outside any shard_map" in out[0].message

    def test_axis_none_reaching_the_trace(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return jax.lax.psum(x.sum(axis=0), None)
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD002"])
        assert any("axis_name=None" in f.message for f in out)

    def test_negative_guarded_degenerate_path(self):
        """`psum(v, axis) if axis is not None else v` called with None
        folds to the identity branch — the single-device path must stay
        legal."""
        out = lint(shard_src("""
            def _allreduce(v, axis_name):
                return jax.lax.psum(v, axis_name) \\
                    if axis_name is not None else v

            def run_local(x):
                return _allreduce(x.sum(axis=0), None)

            def build(mesh):
                def core(x):
                    return _allreduce(x.sum(axis=0), "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD002"])
        assert out == []

    def test_axis_judged_per_site_when_mesh_resolves(self):
        """With the site's Mesh construction statically resolvable, a
        collective naming an axis THAT mesh does not bind flags — even
        though another site in the project binds it (per-site judgment,
        not the global union)."""
        out = lint(shard_src("""
            from jax.sharding import Mesh

            def build_model(mesh):
                def core_m(x):
                    return jax.lax.psum(x.sum(axis=0), "model")
                return shard_map(core_m, mesh,
                                 in_specs=(P("model", None),),
                                 out_specs=P())

            def build_batch(devs):
                mesh = Mesh(devs, ("batch",))
                def core_b(x):
                    return jax.lax.psum(x.sum(axis=0), "model")
                return shard_map(core_b, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD002"])
        assert len(out) == 1
        assert "'model'" in out[0].message and "batch" in out[0].message

    def test_negative_unresolved_mesh_binds_all_declared_axes(self):
        """When the mesh is a parameter (statically opaque), a
        collective over a project-declared axis absent from the specs
        stays legal — shard_map binds EVERY mesh axis, not just the
        spec-listed ones (the 2-D batch x model case)."""
        out = lint(shard_src("""
            BATCH_AXIS = "batch"
            MODEL_AXIS = "model"

            def build(mesh):
                def core(x):
                    w = jax.lax.psum(jnp.ones(()), MODEL_AXIS)
                    return jax.lax.psum(x.sum(axis=0), BATCH_AXIS) / w
                return shard_map(core, mesh,
                                 in_specs=(P(BATCH_AXIS, None),),
                                 out_specs=P())
        """), rules=["SHD002"])
        assert out == []

    def test_negative_tuple_axis_reduction(self):
        """psum over a TUPLE of axes — the 2-D mesh idiom — reduces
        every named axis and must satisfy SHD001's replicated claim."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    return jax.lax.psum(x.sum(axis=0),
                                        ("batch", "model"))
                return shard_map(core, mesh,
                                 in_specs=(P(("batch", "model"), None),),
                                 out_specs=P())
        """), rules=["SHD001", "SHD002"])
        assert out == []

    def test_negative_module_constant_axis_cross_module(self):
        """BATCH_AXIS imported from another module resolves to its
        string value — the ops/ <- parallel/mesh.py idiom."""
        out = lint_many([
            ("pkg/mesh.py", """
                BATCH_AXIS = "batch"
            """),
            ("pkg/kern.py", shard_src("""
                from pkg.mesh import BATCH_AXIS

                def build(mesh):
                    def core(x):
                        return jax.lax.psum(x.sum(axis=0), BATCH_AXIS)
                    return shard_map(core, mesh,
                                     in_specs=(P(BATCH_AXIS, None),),
                                     out_specs=P())
            """))], rules=["SHD002"])
        assert out == []

    def test_negative_none_constant_spec_entry_cross_module(self):
        """An imported constant whose value is None parses as a
        replicated spec entry, not an unknown (sharded) one."""
        out = lint_many([
            ("pkg/mesh.py", """
                BATCH_AXIS = "batch"
                LANE_AXIS = None
            """),
            ("pkg/kern.py", shard_src("""
                from pkg.mesh import BATCH_AXIS, LANE_AXIS

                def build(mesh):
                    def core(x, tbl):
                        return jax.lax.psum(
                            (x * tbl[None, :]).sum(axis=0), BATCH_AXIS)
                    return shard_map(
                        core, mesh,
                        in_specs=(P(BATCH_AXIS, None), P(LANE_AXIS)),
                        out_specs=P())
            """))], rules=["SHD"])
        assert out == []

    def test_same_basename_module_resolves_to_sibling(self):
        """`from pkg.models.mesh import AXIS` with both ops/mesh.py and
        models/mesh.py present resolves the IMPORTING module's sibling
        (path-boundary + nearest-directory match), so the axis constant
        comes from the right file."""
        out = lint_many([
            ("pkg/ops/mesh.py", """
                AXIS = "batch"
            """),
            ("pkg/models/mesh.py", """
                AXIS = "lane"
            """),
            ("pkg/models/kern.py", shard_src("""
                from .mesh import AXIS

                def build(mesh):
                    def core(x):
                        return jax.lax.psum(x.sum(axis=0), AXIS)
                    return shard_map(core, mesh,
                                     in_specs=(P("lane", None),),
                                     out_specs=P())
            """))], rules=["SHD002"])
        assert out == []

    def test_constant_axis_mismatch_cross_module(self):
        """A mesh built by a cross-module factory (make_mesh) resolves
        its axis tuple; a collective naming a different constant's
        axis flags."""
        out = lint_many([
            ("pkg/mesh.py", """
                from jax.sharding import Mesh

                BATCH_AXIS = "batch"
                MODEL_AXIS = "model"

                def make_mesh(devs):
                    return Mesh(devs, (BATCH_AXIS,))
            """),
            ("pkg/kern.py", shard_src("""
                from pkg.mesh import BATCH_AXIS, MODEL_AXIS, make_mesh

                def build(devs):
                    mesh = make_mesh(devs)
                    def core(x):
                        return jax.lax.psum(x.sum(axis=0), MODEL_AXIS)
                    return shard_map(core, mesh,
                                     in_specs=(P(BATCH_AXIS, None),),
                                     out_specs=P())
            """))], rules=["SHD002"])
        assert len(out) == 1 and "'model'" in out[0].message


# -- SHD003: shard-variant nondeterminism ------------------------------------

class TestSHD003:
    def test_index_local_draw_mixing_with_sharded_rows(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x, key):
                    g = x * 2.0
                    rw = (jax.random.uniform(key, (128,)) < 0.5)
                    g = g * rw[:, None]
                    return jax.lax.psum(g.sum(axis=0), "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None), P()),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert len(out) == 1
        assert "index-local" in out[0].message

    def test_host_branch_on_shard_variant_value(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    s = x.sum()
                    if s > 0:
                        s = s * 2.0
                    return jax.lax.psum(s, "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert len(out) == 1
        assert "host control flow" in out[0].message

    def test_negative_trace_time_raise_bars_the_draw(self):
        """The promoted subsample pattern: the `raise` under the axis
        guard is a recorded path condition that kills the draw branch —
        the guarded repo shape scans clean."""
        out = lint(shard_src("""
            def impl(x, key, subsample, axis_name):
                if subsample < 1.0 and axis_name is not None:
                    raise ValueError("no sharded subsample")
                g = x * 2.0
                if subsample < 1.0:
                    rw = (jax.random.uniform(key, (128,)) < subsample)
                    g = g * rw[:, None]
                return jax.lax.psum(g.sum(axis=0), axis_name)

            def build(mesh, subsample):
                def core(x, key):
                    return impl(x, key, subsample, axis_name="batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None), P()),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert out == []

    def test_removing_the_raise_reintroduces_the_finding(self):
        """Same shape minus the trace-time bar: SHD003 catches in CI
        what used to only raise at trace time."""
        out = lint(shard_src("""
            def impl(x, key, subsample, axis_name):
                g = x * 2.0
                if subsample < 1.0:
                    rw = (jax.random.uniform(key, (128,)) < subsample)
                    g = g * rw[:, None]
                return jax.lax.psum(g.sum(axis=0), axis_name)

            def build(mesh, subsample):
                def core(x, key):
                    return impl(x, key, subsample, axis_name="batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None), P()),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert len(out) == 1

    def test_where_mask_application_also_flags(self):
        """The canonical jnp.where mask application is the same
        index-local bug as `x * mask` and must not hide behind the
        generic call join."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x, key):
                    mask = jax.random.uniform(key, (128,)) < 0.5
                    w = jnp.where(mask[:, None], x, 0.0)
                    return jax.lax.psum(w.sum(axis=0), "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None), P()),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert len(out) == 1
        assert "jnp.where" in out[0].message

    def test_negative_replicated_feature_draw(self):
        """A draw that only ever combines with replicated data (the
        colsample feature-mask shape) is shard-consistent — same key,
        same subset on every shard."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x, key):
                    hist = jax.lax.psum(x.sum(axis=0), "batch")
                    fmask = jax.random.uniform(key, (16,)) < 0.5
                    gain = jnp.where(fmask, hist, -jnp.inf)
                    return gain
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None), P()),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert out == []

    def test_negative_pytree_none_check_is_static(self):
        """`x.gzz is None` structure checks are trace-time static and
        must not count as host branching."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    extra = None
                    if extra is None:
                        y = x.sum(axis=0)
                    else:
                        y = x.sum(axis=0) + extra
                    return jax.lax.psum(y, "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD003"])
        assert out == []


# -- SHD004: spec arity/rank mismatch ----------------------------------------

class TestSHD004:
    def test_in_specs_arity_mismatch(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x, y):
                    return jax.lax.psum(x + y, "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch"),),
                                 out_specs=P())
        """), rules=["SHD004"])
        assert len(out) == 1
        assert "1 entry" in out[0].message and "2" in out[0].message

    def test_out_specs_count_mismatch(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    s = jax.lax.psum(x.sum(axis=0), "batch")
                    return s, s, s
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=(P(), P()))
        """), rules=["SHD004"])
        assert len(out) == 1
        assert "out_specs has 2" in out[0].message

    def test_rank_mismatch_against_shape_unpack(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x):
                    n, d = x.shape
                    return jax.lax.psum(x.sum(axis=0), "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None, None),),
                                 out_specs=P())
        """), rules=["SHD004"])
        assert len(out) == 1
        assert "rank-2" in out[0].message

    def test_negative_vararg_core_with_repeated_specs(self):
        """The stats-engine `core(X, y, w, *extras)` shape with
        `(P(...),)*n` repeated specs has no static arity to violate."""
        out = lint(shard_src("""
            def build(mesh, n_extras):
                def core(x, y, *extras):
                    return jax.lax.psum((x * y[:, None]).sum(axis=0),
                                        "batch")
                return shard_map(
                    core, mesh,
                    in_specs=(P("batch", None), P("batch"))
                    + (P(),) * n_extras,
                    out_specs=P())
        """), rules=["SHD004"])
        assert out == []

    def test_negative_defaulted_param_may_go_unmapped(self):
        """shard_map specs match the CALL's argument pytree, not the
        signature — a trailing defaulted param with no spec is legal."""
        out = lint(shard_src("""
            def build(mesh):
                def core(x, scale=1.0):
                    return jax.lax.psum((x * scale).sum(axis=0),
                                        "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """), rules=["SHD004"])
        assert out == []

    def test_negative_exact_arity(self):
        out = lint(shard_src("""
            def build(mesh):
                def core(x, y):
                    return jax.lax.psum(x + y, "batch")
                return shard_map(core, mesh,
                                 in_specs=(P("batch"), P("batch")),
                                 out_specs=P())
        """), rules=["SHD004"])
        assert out == []


# -- SHD005: host merge without the cross-process fold -----------------------

class TestSHD005:
    def test_np_sum_over_fetched_sharded_array(self):
        out = lint("""
            import numpy as np
            from pkg.parallel import multihost

            def run(local, n):
                mesh = multihost.global_mesh()
                arr = multihost.host_local_rows(local, mesh, n)
                rows = np.asarray(arr)
                return np.sum(rows)
        """, rules=["SHD005"])
        assert len(out) == 1
        assert "addressable shards" in out[0].message

    def test_method_sum_on_fetched_value(self):
        out = lint("""
            import numpy as np
            from pkg.parallel import multihost

            def run(X, mesh):
                multihost.initialize()
                arr, n = multihost_put(X)
                fetched = np.asarray(fit_stats_sharded(mesh, arr))
                return fetched.sum()

            def multihost_put(X):
                return multihost.host_local_rows(X, None, 4), 4
        """, rules=["SHD005"])
        assert len(out) == 1

    def test_branch_assigned_producer_still_caught(self):
        """A sharded producer assigned inside an if-branch is seen by an
        outer-level fetch (the taint pass iterates to a fixpoint —
        ast.walk order must not matter)."""
        out = lint("""
            import numpy as np
            from pkg.parallel import multihost

            def run(local, n, small):
                mesh = multihost.global_mesh()
                if small:
                    arr = multihost.host_local_rows(local[:n], mesh, n)
                else:
                    arr = multihost.host_local_rows(local, mesh, n)
                rows = np.asarray(arr)
                return np.sum(rows)
        """, rules=["SHD005"])
        assert len(out) == 1

    def test_negative_reduce_on_device_before_fetch(self):
        """psum inside the sharded program, host just reads the already
        -global scalar: the documented-correct shape."""
        out = lint("""
            import numpy as np
            from pkg.parallel import multihost

            def run(local, n, fitted):
                mesh = multihost.global_mesh()
                arr = multihost.host_local_rows(local, mesh, n)
                total = np.asarray(device_total(arr))
                return total
        """, rules=["SHD005"])
        assert out == []

    def test_negative_single_process_module_untouched(self):
        out = lint("""
            import numpy as np

            def run(x):
                rows = np.asarray(x)
                return np.sum(rows)
        """, rules=["SHD005"])
        assert out == []


# -- ENV001: knob registry ---------------------------------------------------

class TestENV001:
    def test_unregistered_knob_read(self):
        out = lint("""
            import os

            def f():
                return os.environ.get("TMOG_TOTALLY_NEW_KNOB", "1")
        """, rules=["ENV001"])
        assert len(out) == 1
        assert "TMOG_TOTALLY_NEW_KNOB" in out[0].message

    def test_env_on_and_subscript_reads_also_checked(self):
        out = lint("""
            import os

            def f():
                a = env_on("TMOG_NOT_REGISTERED_A")
                b = os.environ["TMOG_NOT_REGISTERED_B"]
                return a, b
        """, rules=["ENV001"])
        assert sorted("TMOG_NOT_REGISTERED" in f.message
                      for f in out) == [True, True]

    def test_setdefault_and_membership_reads_also_checked(self):
        """environ.setdefault and `"TMOG_X" in os.environ` establish
        knob-dependent behavior just like .get — same registry
        contract."""
        out = lint("""
            import os

            def f():
                os.environ.setdefault("TMOG_NOT_REGISTERED_C", "1")
                if "TMOG_NOT_REGISTERED_D" in os.environ:
                    return True
                return False
        """, rules=["ENV001"])
        assert len(out) == 2

    def test_negative_registered_knob(self):
        out = lint("""
            import os

            def f():
                return os.environ.get("TMOG_TREE_SCAN", "")
        """, rules=["ENV001"])
        assert out == []

    def test_registry_row_missing_from_doc(self, tmp_path):
        out = lint_tree(tmp_path, {
            "docs/perf.md": "Only `TMOG_DOCUMENTED` is described here.",
            "knobs.py": """
                KNOBS = [
                    {"name": "TMOG_DOCUMENTED", "default": "1",
                     "doc": "docs/perf.md", "desc": "fine"},
                    {"name": "TMOG_FORGOTTEN", "default": "1",
                     "doc": "docs/perf.md", "desc": "drifted"},
                ]
            """,
            "mod.py": """
                import os
                x = os.environ.get("TMOG_DOCUMENTED", "")
            """,
        }, rules=["ENV001"])
        assert len(out) == 1
        assert "TMOG_FORGOTTEN" in out[0].message
        assert out[0].path == "knobs.py"

    def test_doc_mention_is_boundary_aware(self, tmp_path):
        """A knob that is a PREFIX of a documented knob must not pass
        on the longer name's mentions (the TMOG_COMPILE_CACHE /
        TMOG_COMPILE_CACHE_DIR case)."""
        out = lint_tree(tmp_path, {
            "docs/perf.md": "Set `TMOG_CACHE_DIR` to a directory.",
            "knobs.py": """
                KNOBS = [
                    {"name": "TMOG_CACHE_DIR", "default": "",
                     "doc": "docs/perf.md", "desc": "fine"},
                    {"name": "TMOG_CACHE", "default": "",
                     "doc": "docs/perf.md", "desc": "prefix of above"},
                ]
            """,
        }, rules=["ENV001"])
        assert len(out) == 1 and "TMOG_CACHE" in out[0].message

    def test_registry_row_with_missing_doc_file(self, tmp_path):
        out = lint_tree(tmp_path, {
            "knobs.py": """
                KNOBS = [
                    {"name": "TMOG_X", "default": "1",
                     "doc": "docs/nope.md", "desc": "orphan"},
                ]
            """,
        }, rules=["ENV001"])
        assert len(out) == 1 and "does not exist" in out[0].message

    def test_real_registry_matches_real_code_and_docs(self):
        """The committed registry covers every TMOG_* read in the repo
        and every row's doc file mentions its knob — scanned exactly as
        ci.sh step 2 does."""
        ctxs, errors = scan_paths(
            ["transmogrifai_tpu", "tests", "bench.py", "tools"],
            REPO_ROOT)
        out = [f for f in errors + run_rules(ctxs, only=["ENV001"])
               if f.rule == "ENV001"]
        assert out == [], "\n".join(f.render() for f in out)


# -- EVT001: event schema ----------------------------------------------------

EVT_DOC = """
    # Observability

    ## The event log (`events.jsonl`)

    | event | source | fields |
    |---|---|---|
    | `alpha_done` / `alpha_start` | pkg/mod.py | `n` |
    | `beta_tick` | pkg/mod.py | `t` |
"""


class TestEVT001:
    def test_unlisted_event_name(self, tmp_path):
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/mod.py": """
                def f(log):
                    log.event("alpha_done", n=1)
                    log.event("alpha_start", n=1)
                    log.event("beta_tick", t=0.0)
                    log.event("gamma_unlisted", x=2)
            """,
        }, rules=["EVT001"])
        assert len(out) == 1
        assert "gamma_unlisted" in out[0].message

    def test_stale_table_row(self, tmp_path):
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/mod.py": """
                def f(log):
                    log.event("alpha_done", n=1)
                    log.event("alpha_start", n=1)
            """,
        }, rules=["EVT001"])
        assert len(out) == 1
        assert "beta_tick" in out[0].message
        assert out[0].path == "docs/observability.md"

    def test_subtree_scan_still_checks_call_sites(self, tmp_path):
        """Scanning a package SUBDIRECTORY (its own __init__.py in the
        scan, the top-level one absent) still runs the unlisted-name
        direction — only the stale direction needs the whole package."""
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/serve/__init__.py": "",
            "pkg/serve/mod.py": """
                def f(log):
                    log.event("serve_new_thing", x=1)
            """,
        }, paths=("pkg/serve",), rules=["EVT001"])
        assert len(out) == 1
        assert "serve_new_thing" in out[0].message
        assert all("stale" not in f.message for f in out)

    def test_stale_scoping_needs_full_package_view(self, tmp_path):
        """Without the package __init__.py in the scan, unmatched table
        rows are NOT stale — a single-file scan cannot judge the
        package's full emitter set."""
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/mod.py": """
                def f(log):
                    log.event("alpha_done", n=1)
            """,
        }, paths=("pkg/mod.py",), rules=["EVT001"])
        assert out == []

    def test_negative_all_listed_and_emitted(self, tmp_path):
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/mod.py": """
                def f(log):
                    log.event("alpha_done", n=1)
                    log.event("alpha_start", n=1)
                    log.event("beta_tick", t=0.0)
            """,
        }, rules=["EVT001"])
        assert out == []

    def test_stale_needs_an_emitting_package_not_any_package(self,
                                                             tmp_path):
        """Scanning a package that emits NO events (the tools/ case)
        must not declare the event table stale, even though its
        __init__.py is in the scan."""
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "toolpkg/__init__.py": "",
            "toolpkg/util.py": "def f():\n    return 1\n",
        }, paths=("toolpkg",), rules=["EVT001"])
        assert out == []

    def test_negative_tests_and_scripts_out_of_scope(self, tmp_path):
        """Only package files (top dir with a scanned __init__.py) are
        checked: tests may emit fixture events freely."""
        out = lint_tree(tmp_path, {
            "docs/observability.md": EVT_DOC,
            "pkg/__init__.py": "",
            "pkg/mod.py": """
                def f(log):
                    log.event("alpha_done", n=1)
                    log.event("alpha_start", n=1)
                    log.event("beta_tick", t=0.0)
            """,
            "tests/test_mod.py": """
                def test_f(log):
                    log.event("made_up_fixture_event")
            """,
        }, rules=["EVT001"])
        assert out == []

    def test_real_event_table_matches_real_emitters(self):
        """Code <-> docs/observability.md table, both directions, on
        the real repo."""
        ctxs, errors = scan_paths(["transmogrifai_tpu", "tests"],
                                  REPO_ROOT)
        out = [f for f in errors + run_rules(ctxs, only=["EVT001"])
               if f.rule == "EVT001"]
        assert out == [], "\n".join(f.render() for f in out)


# -- the repo's own sharded modules scan clean -------------------------------

class TestRepoShardedModulesClean:
    @pytest.fixture(scope="class")
    def shd_findings(self):
        ctxs, errors = scan_paths(["transmogrifai_tpu"], REPO_ROOT)
        return errors + run_rules(ctxs, only=["SHD"])

    def test_all_sharded_ops_modules_clean(self, shd_findings):
        """Every shard_map site in ops/stats_engine, ops/trees,
        ops/glm_sweep, parallel/* proves its out_spec claims — the
        acceptance pin that the baseline stays EMPTY with SHD on."""
        assert shd_findings == [], \
            "\n".join(f.render() for f in shd_findings)

    def test_sites_actually_discovered(self):
        """The clean scan must not be vacuous: the analysis resolves
        the repo's real shard_map sites and proves replicated outputs
        reduced (not 'skipped')."""
        from tools.tmoglint.shardflow import ShardAnalysis
        ctxs, _ = scan_paths(["transmogrifai_tpu"], REPO_ROOT)
        sa = ShardAnalysis(ctxs)
        paths = {s.mod.path for s in sa.sites}
        for expected in ("transmogrifai_tpu/ops/stats_engine.py",
                         "transmogrifai_tpu/ops/glm_sweep.py",
                         "transmogrifai_tpu/ops/trees.py"):
            assert expected in paths, sorted(paths)
        assert len(sa.sites) >= 8
        assert not sa.any_incomplete
        # the collective observations bind the real mesh axis
        axes = set()
        for _mod, _node, _tail, per_site in sa.collectives.values():
            for vals in per_site.values():
                for v in vals:
                    if isinstance(v, frozenset):
                        axes |= v
        assert "batch" in axes


# -- the subsample bar: both layers pinned -----------------------------------

class TestSubsampleBarBothLayers:
    def test_trace_time_raise_still_fires(self):
        """Layer 1 (backstop): the sharded fused fit refuses
        subsample<1 at trace time."""
        from transmogrifai_tpu.ops.trees import _fit_gbt_folds_impl
        Xb = np.zeros((8, 3), np.int8)
        y = np.zeros(8, np.float32)
        W = np.ones((2, 8), np.float32)
        with pytest.raises(ValueError, match="subsample"):
            _fit_gbt_folds_impl(Xb, y, W, None, n_rounds=1, depth=2,
                                n_bins=4, subsample=0.5,
                                axis_name="batch")

    def test_lint_time_layer_catches_it_first(self):
        """Layer 2 (SHD003): the real ops/trees.py guard is recognized
        (clean scan, asserted above); the fixture in
        TestSHD003.test_removing_the_raise_reintroduces_the_finding
        proves removing the guard flags at lint time, before any sweep
        runs. Here: the real module, scanned alone with its imports,
        stays clean under SHD003."""
        ctxs, _ = scan_paths(["transmogrifai_tpu/ops",
                              "transmogrifai_tpu/parallel"], REPO_ROOT)
        out = [f for f in run_rules(ctxs, only=["SHD003"])]
        assert out == [], "\n".join(f.render() for f in out)


# -- CLI: family selection, scoping, parallel parity -------------------------

class TestCLIFamilies:
    def _run(self, args, cwd=REPO_ROOT):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        return subprocess.run(
            [sys.executable, "-m", "tools.tmoglint"] + args,
            cwd=cwd, env=env, capture_output=True, text=True)

    def test_family_selection_shd_env_evt(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""
            import os
            import jax

            @jax.jit
            def f(x):
                return jax.lax.psum(x, "batch")

            FLAG = os.environ.get("TMOG_NOT_A_REAL_KNOB", "")
        """))
        proc = self._run(["mod.py", "--root", str(tmp_path),
                          "--no-baseline", "--rules", "SHD,ENV,EVT",
                          "--format", "json"])
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["rules"] == ["ENV001", "EVT001", "SHD001",
                                   "SHD002", "SHD003", "SHD004",
                                   "SHD005"]
        assert report["counts_by_rule"] == {"ENV001": 1, "SHD002": 1}

    def test_scoping_guard_composes_with_new_families(self, tmp_path):
        """A baselined TPU entry is out of scope for a SHD-only scan:
        neither new nor stale."""
        (tmp_path / "clean.py").write_text("x = 1\n")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"version": 1, "findings": [
            {"fingerprint": "feedfeedfeedfeed", "rule": "TPU003",
             "path": "other.py", "line": 1, "col": 0,
             "message": "unrelated grandfathered debt", "snippet": ""}]}))
        proc = self._run(["clean.py", "--root", str(tmp_path),
                          "--baseline", str(base), "--rules", "SHD",
                          "--format", "json"])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["stale_baseline_entries"] == []

    def test_parallel_jobs_match_serial_with_new_families(self, tmp_path):
        """--jobs 1 and --jobs 2 produce identical reports with SHD/
        ENV/EVT findings present (they are project rules — the pool
        split must not change them)."""
        (tmp_path / "kern.py").write_text(textwrap.dedent("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def build(mesh):
                def core(x):
                    return x.sum(axis=0)
                return shard_map(core, mesh,
                                 in_specs=(P("batch", None),),
                                 out_specs=P())
        """))
        (tmp_path / "knob.py").write_text(textwrap.dedent("""
            import os
            FLAG = os.environ.get("TMOG_NOT_A_REAL_KNOB_2", "")
        """))
        for i in range(4):
            (tmp_path / f"filler{i}.py").write_text(f"x = {i}\n")
        outs = []
        for jobs in ("1", "2"):
            proc = self._run([".", "--root", str(tmp_path),
                              "--no-baseline", "--jobs", jobs,
                              "--format", "json"])
            assert proc.returncode == 1, proc.stdout + proc.stderr
            report = json.loads(proc.stdout)
            outs.append([(f["rule"], f["path"], f["fingerprint"])
                         for f in report["new"]])
        assert outs[0] == outs[1]
        assert {r for r, _, _ in outs[0]} >= {"SHD001", "ENV001"}

    def test_list_rules_includes_new_families(self):
        proc = self._run(["--list-rules"])
        assert proc.returncode == 0
        for rid in ("SHD001", "SHD002", "SHD003", "SHD004", "SHD005",
                    "ENV001", "EVT001"):
            assert rid in proc.stdout
