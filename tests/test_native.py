"""Native C++ host kernels vs pure-Python reference implementations.

Parity gates: murmur3 test vectors, batch hashing == python hashing,
fused tokenize+hash == tokenize_text + hash_tokens_to_counts, CSV scan ==
python csv module. Skipped only if the baked-in g++ somehow fails.
"""
import csv as pycsv
import io

import numpy as np
import pytest

from transmogrifai_tpu.ops import native_bridge as NB
from transmogrifai_tpu.ops.hashing import (
    hash_string, hash_tokens_to_counts, murmur3_32)

pytestmark = pytest.mark.skipif(not NB.available(),
                                reason="native library unavailable")


class TestMurmur:
    def test_reference_vectors(self):
        # canonical MurmurHash3_x86_32 test vectors
        assert NB.native_murmur3(b"", 0) == 0
        assert NB.native_murmur3(b"", 1) == 0x514E28B7
        assert NB.native_murmur3(b"abc", 0) == 0xB3DD93FA
        assert NB.native_murmur3(b"Hello, world!", 1234) == 0xFAF6CDB3

    def test_matches_python(self):
        rng = np.random.default_rng(0)
        for _ in range(50):
            n = int(rng.integers(0, 40))
            data = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
            seed = int(rng.integers(0, 2**31))
            assert NB.native_murmur3(data, seed) == murmur3_32(data, seed)


class TestBatchHashing:
    def test_hash_strings_matches(self):
        strings = ["hello", "world", "", "héllo ünïcode", "a" * 100]
        out = NB.native_hash_strings(strings, seed=7)
        for s, h in zip(strings, out):
            assert int(h) == murmur3_32(s.encode("utf-8"), 7)

    def test_hash_tokens_matches_python_fallback(self):
        token_lists = [["the", "cat"], None, [], ["cat", "cat", "dog"]]
        import os
        native = NB.native_hash_tokens(token_lists, 32, seed=3)
        # pure python path
        py = np.zeros((4, 32))
        for i, toks in enumerate(token_lists):
            for t in (toks or []):
                py[i, hash_string(t, 32, 3)] += 1
        np.testing.assert_array_equal(native, py)

    def test_fused_tokenizer_matches_python_pipeline(self):
        # contract: the byte-level C tokenizer equals the unicode python
        # analyzer on ASCII documents (the only inputs it is routed)
        from transmogrifai_tpu.transformers.text import tokenize_text
        docs = ["The CAT sat on the mat!", None, "", "123's it's-fine",
                "a,b;c  d\te", "under_score splits"]
        fused = NB.native_tokenize_hash_counts(docs, 64, seed=1, min_len=1)
        py = np.zeros((len(docs), 64))
        for i, d in enumerate(docs):
            for t in tokenize_text(d, 1, True, False):
                py[i, hash_string(t, 64, 1)] += 1
        np.testing.assert_array_equal(fused, py)

    def test_non_ascii_docs_route_to_unicode_python_path(self):
        from transmogrifai_tpu.automl.vectorizers.text import (
            tokenize, tokenize_hash_counts)
        docs = ["naïve café crème", "北京 大学", None]
        out = tokenize_hash_counts(docs, 32, seed=2)
        py = np.zeros((len(docs), 32))
        for i, d in enumerate(docs):
            for t in tokenize(d):
                py[i, hash_string(t, 32, 2)] += 1
        np.testing.assert_array_equal(out, py)
        assert out[1].sum() == 2.0  # unicode tokens kept, not dropped


class TestCSV:
    def test_csv_scan_matches_csv_module(self):
        text = ('a,b,c\n1,"two, with comma",3\r\n'
                '"quoted ""inner"" text",5,\n,,\n')
        native = NB.native_csv_parse(text.encode("utf-8"))
        expected = list(pycsv.reader(io.StringIO(text)))
        assert native == expected

    def test_csv_non_ascii_utf8(self):
        # regression: field bounds are BYTE offsets; multi-byte characters
        # must not shift later fields (José is 5 bytes / 4 chars)
        text = ('name,city,score\nJosé,Köln,1.5\n"Fran ""çois""",東京,2\n'
                'plain,row,3\n')
        native = NB.native_csv_parse(text.encode("utf-8"))
        expected = list(pycsv.reader(io.StringIO(text)))
        assert native == expected

    def test_parse_floats(self):
        data = b"1.5,-2e3, ,abc,42"
        bounds = np.array([0, 3, 4, 8, 9, 10, 11, 14, 15, 17], np.int64)
        out = NB.native_parse_floats(data, bounds)
        assert out[0] == 1.5 and out[1] == -2000.0 and out[4] == 42.0
        assert np.isnan(out[2]) and np.isnan(out[3])


class TestIntegration:
    def test_hashing_vectorizer_uses_native(self):
        # hash_tokens_to_counts routes through native when available and
        # must equal the pure python result
        token_lists = [["x", "y"], ["x"], None]
        out = hash_tokens_to_counts(token_lists, 16, seed=0)
        py = np.zeros((3, 16))
        for i, toks in enumerate(token_lists):
            for t in (toks or []):
                py[i, hash_string(t, 16, 0)] += 1
        np.testing.assert_array_equal(out, py)


def test_native_dict_encode_matches_numpy_unique():
    from transmogrifai_tpu.ops.native_bridge import native_dict_encode
    import numpy as np
    rng = np.random.default_rng(3)
    strs = [f"v{int(i)}" for i in rng.integers(0, 37, size=5000)]
    out = native_dict_encode(strs)
    if out is None:
        import pytest
        pytest.skip("native library unavailable")
    codes, uniques = out
    # exact decode round-trip
    assert [uniques[c] for c in codes] == strs
    # same unique SET as np.unique (order differs by design)
    arr = np.empty(len(strs), object); arr[:] = strs
    assert set(uniques) == set(np.unique(arr))
    # unicode + empties + collisions in one table
    c, u = native_dict_encode(["", "ü", "", "a" * 300, "ü"])
    assert list(c) == [0, 1, 0, 2, 1] and u == ["", "ü", "a" * 300]


def test_factorize_native_and_fallback_agree(monkeypatch):
    import numpy as np
    from transmogrifai_tpu.automl.vectorizers import encoding as E
    data = ["b", None, "a", "b", 7, None, "a"]
    u1, inv1, nm1 = E.factorize(data)
    # force the numpy fallback
    import transmogrifai_tpu.ops.native_bridge as NB
    monkeypatch.setattr(NB, "native_dict_encode", lambda s: None)
    u2, inv2, nm2 = E.factorize(data)
    # decode both: identical value streams regardless of unique order
    assert [u1[i] for i in inv1] == [u2[i] for i in inv2]
    np.testing.assert_array_equal(nm1, nm2)
