"""Workflow-level cross-validation (reference OpWorkflowCVTest.scala):
in-fold refit of the pre-selector DAG, winner equivalence with the plain
path on clean data, and summary contents.
"""
import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.workflow import Workflow


def _rows(n=400, seed=21):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        x = float(rng.normal())
        z = float(rng.normal())
        rows.append({"x": x, "z": z, "cat": str(int(rng.integers(0, 3))),
                     "label": float(x + 0.3 * z + rng.normal(0, 0.4) > 0)})
    return rows


def _workflow(cv=False):
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    fz = FeatureBuilder.Real("z").extract(lambda r: r.get("z")).as_predictor()
    fc = FeatureBuilder.PickList("cat").extract(
        lambda r: r.get("cat")).as_predictor()
    fy = FeatureBuilder.RealNN("label").extract(
        lambda r: r.get("label")).as_response()
    vec = transmogrify([fx, fz, fc])
    checked = SanityChecker().set_input(fy, vec).get_output()
    pred = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=11,
        models_and_parameters=[
            (OpLogisticRegression(), param_grid(reg_param=[0.01, 0.1])),
            (OpGBTClassifier(), param_grid(max_iter=[5], max_depth=[2])),
        ]).set_input(fy, checked).get_output()
    wf = Workflow().set_reader(ListReader(_rows())) \
        .set_result_features(pred)
    return wf.with_workflow_cv() if cv else wf


def test_workflow_cv_trains_and_flags_results():
    model = _workflow(cv=True).train()
    summary = model.selector_summary()
    wf_cv = [v for v in summary.validation_results
             if v.get("workflow_cv")]
    # full sweep (2 LR grids + 1 GBT) validated with in-fold DAG refits
    assert len(wf_cv) == 3
    assert all(len(v["fold_metrics"]) == 3 for v in wf_cv)
    # selector then refit only the winner
    plain = [v for v in summary.validation_results
             if not v.get("workflow_cv")]
    assert len(plain) == 1
    assert model.summary_pretty()


def test_workflow_cv_scores_and_matches_plain_winner():
    # on linearly-separable-ish data both paths must pick logistic
    m_cv = _workflow(cv=True).train()
    m_plain = _workflow(cv=False).train()
    assert m_cv.selector_summary().best_model_type == \
        m_plain.selector_summary().best_model_type == "OpLogisticRegression"
    scored = m_cv.score()
    assert scored.n_rows == 400


def test_workflow_cv_without_selector_is_noop():
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    vec = transmogrify([fx])
    wf = Workflow().set_reader(ListReader(_rows())) \
        .set_result_features(vec).with_workflow_cv()
    model = wf.train()  # must not raise
    assert model.transform().n_rows == 400


def test_workflow_cv_glm_takes_device_route():
    """The inner (model x grid) sweep runs through the validator's device
    paths — fold-masked vmapped lanes for GLM candidates, mask-fold trees
    for the GBT — not a host fit_arrays loop (reference parallelism slot:
    OpValidator.scala:318's 8-thread pool)."""
    wf = _workflow(cv=True)
    model = wf.train()
    routes = getattr(wf, "_workflow_cv_routes", {})
    assert routes, "workflow CV recorded no sweep routes"
    summary = model.selector_summary()
    wf_cv = [v for v in summary.validation_results if v.get("workflow_cv")]
    by_model = {}
    for key, route in routes.items():
        mi, _ = key
        by_model.setdefault(mi, set()).add(route)
    # model 0 = OpLogisticRegression grids, model 1 = OpGBTClassifier
    assert by_model[0] == {"vmapped"}, by_model
    assert by_model[1] == {"mask_folds"}, by_model
    # and the full sweep still covers every cell across 3 folds
    assert len(wf_cv) == 3
    assert all(len(v["fold_metrics"]) == 3 for v in wf_cv)
