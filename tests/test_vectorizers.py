"""Vectorizer + Transmogrifier tests (reference vectorizer suites)."""
import numpy as np
import pytest

from transmogrifai_tpu import (
    Binary, Dataset, FeatureBuilder, Geolocation, Integral, MultiPickList,
    PickList, Real, RealMap, RealNN, Text, TextList, TextMap,
)
from transmogrifai_tpu.automl.transmogrifier import transmogrify, vectorize_by_type
from transmogrifai_tpu.automl.vectorizers.categorical import OneHotVectorizer
from transmogrifai_tpu.automl.vectorizers.combiner import VectorsCombiner
from transmogrifai_tpu.automl.vectorizers.maps import MapVectorizer
from transmogrifai_tpu.automl.vectorizers.numeric import (
    NumericBucketizer, NumericVectorizer,
)
from transmogrifai_tpu.automl.vectorizers.text import SmartTextVectorizer, tokenize
from transmogrifai_tpu.ops.hashing import murmur3_32


def test_murmur3_reference_vectors():
    # standard MurmurHash3_x86_32 test vectors
    assert murmur3_32(b"") == 0
    assert murmur3_32(b"", seed=1) == 0x514E28B7
    assert murmur3_32(b"abc") == 0xB3DD93FA
    assert murmur3_32(b"Hello, world!", seed=1234) == 0xFAF6CDB3


def test_numeric_vectorizer_mean_impute_and_nulls():
    x = FeatureBuilder.Real("x").as_predictor()
    z = FeatureBuilder.Real("z").as_predictor()
    ds = Dataset.from_features([
        ("x", Real, [1.0, None, 3.0]),
        ("z", Real, [10.0, 20.0, None]),
    ])
    vec = NumericVectorizer().set_input(x, z)
    model = vec.fit(ds)
    out = model.transform(ds)
    col = out.column(model.output_name())
    # layout: x, x_null, z, z_null
    np.testing.assert_allclose(
        col.data,
        [[1.0, 0.0, 10.0, 0.0], [2.0, 1.0, 20.0, 0.0], [3.0, 0.0, 15.0, 1.0]])
    md = col.metadata
    assert md.size == 4
    assert md.columns[1].is_null_indicator
    assert md.columns[0].parent_feature_name == "x"
    # row-level parity
    v = model.transform_value(Real(None), Real(5.0))
    np.testing.assert_allclose(v.value, [2.0, 1.0, 5.0, 0.0])


def test_onehot_pivot_topk_other_null():
    s = FeatureBuilder.PickList("s").as_predictor()
    vals = ["a"] * 5 + ["b"] * 3 + ["c"] * 1 + [None]
    ds = Dataset.from_features([("s", PickList, vals)])
    vec = OneHotVectorizer(top_k=2, min_support=2).set_input(s)
    model = vec.fit(ds)
    out = model.transform(ds).column(model.output_name())
    md = out.metadata
    # vocab: a, b (c dropped by min_support); cols: a, b, OTHER, NULL
    assert md.column_names() == ["s_s_a", "s_s_b", "s_s_OTHER",
                                 "s_s_NullIndicatorValue"]
    np.testing.assert_allclose(out.data[0], [1, 0, 0, 0])
    np.testing.assert_allclose(out.data[8], [0, 0, 1, 0])  # 'c' -> OTHER
    np.testing.assert_allclose(out.data[9], [0, 0, 0, 1])  # None -> NULL


def test_onehot_clean_text():
    s = FeatureBuilder.PickList("s").as_predictor()
    ds = Dataset.from_features([("s", PickList, ["A!", "a", "  a ", "b.", None] * 3)])
    model = OneHotVectorizer(top_k=5, min_support=1).set_input(s).fit(ds)
    out = model.transform(ds).column(model.output_name())
    # "A!", "a", "  a " all clean to "a"
    assert out.metadata.column_names()[0] == "s_s_a"
    assert out.data[:3, 0].sum() == 3.0


def test_smart_text_dispatch():
    lo = FeatureBuilder.Text("lo").as_predictor()
    hi = FeatureBuilder.Text("hi").as_predictor()
    lo_vals = ["x", "y"] * 10
    hi_vals = [f"word{i} hello" for i in range(20)]
    ds = Dataset.from_features([("lo", Text, lo_vals), ("hi", Text, hi_vals)])
    vec = SmartTextVectorizer(max_cardinality=5, num_features=16,
                              min_support=1).set_input(lo, hi)
    model = vec.fit(ds)
    assert model.plans[0]["mode"] == "pivot"
    assert model.plans[1]["mode"] == "hash"
    out = model.transform(ds).column(model.output_name())
    # lo: 2 vocab + OTHER + NULL = 4; hi: 16 bins + NULL = 17
    assert out.data.shape == (20, 4 + 17)
    assert out.metadata.size == 21


def test_bucketizer_quantiles():
    x = FeatureBuilder.Real("x").as_predictor()
    ds = Dataset.from_features([("x", Real, list(map(float, range(100))) + [None])])
    model = NumericBucketizer(num_buckets=4).set_input(x).fit(ds)
    out = model.transform(ds).column(model.output_name())
    assert out.data.shape[1] == 5  # 4 buckets + null
    assert out.data[0, 0] == 1.0 and out.data[99, 3] == 1.0
    assert out.data[100, 4] == 1.0  # null indicator
    assert out.data[:100, :4].sum() == 100.0


def test_map_vectorizer_real_and_text():
    rm = FeatureBuilder.RealMap("rm").as_predictor()
    tm = FeatureBuilder.PickListMap("tm").as_predictor()
    ds = Dataset.from_features([
        ("rm", RealMap, [{"a": 1.0, "b": 2.0}, {"a": 3.0}, None]),
        ("tm", TextMap, [{"k": "u"}, {"k": "v"}, {"k": "u"}]),
    ])
    from transmogrifai_tpu.types import PickListMap
    vec = MapVectorizer(min_support=1).set_input(rm, tm)
    model = vec.fit(ds)
    out = model.transform(ds).column(model.output_name())
    names = out.metadata.column_names()
    # rm keys a,b -> value+null each = 4 cols; tm key k -> u,v,OTHER,NULL = 4
    assert len(names) == 8
    np.testing.assert_allclose(out.data[1][:4], [3.0, 0.0, 2.0, 1.0])


def test_transmogrify_dispatch_and_combine():
    feats = [
        FeatureBuilder.Real("age").as_predictor(),
        FeatureBuilder.Integral("sibsp").as_predictor(),
        FeatureBuilder.Binary("alone").as_predictor(),
        FeatureBuilder.PickList("sex").as_predictor(),
    ]
    ds = Dataset.from_features([
        ("age", Real, [22.0, None, 35.0, 40.0] * 5),
        ("sibsp", Integral, [1, 0, None, 2] * 5),
        ("alone", Binary, [True, False, None, True] * 5),
        ("sex", PickList, ["m", "f", "f", None] * 5),
    ])
    combined = transmogrify(feats)
    assert combined.feature_type.__name__ == "OPVector"
    # fit the DAG manually: vectorizers then combiner
    stages = {}
    for vf in combined.parents:
        est = vf.origin_stage
        model = est.fit(ds)
        ds = model.transform(ds)
    out = combined.origin_stage.transform(ds).column(combined.name)
    # age: 2; sibsp: 2; alone: 2; sex: m,f,OTHER,NULL=4 (min_support=10 on 20 rows:
    # m appears 5, f 10 -> only f kept => 1+2 extra) — just sanity-check shape & md
    assert out.data.shape[0] == 20
    assert out.metadata.size == out.data.shape[1]
    parents = {c.parent_feature_name for c in out.metadata.columns}
    assert parents == {"age", "sibsp", "alone", "sex"}


def test_tokenize():
    assert tokenize("Hello, World! foo") == ["hello", "world", "foo"]
    assert tokenize(None) == []
