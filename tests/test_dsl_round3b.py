"""Round-3 dsl breadth: parse_phone, idf, deindexed, collect/filter_not,
smart_vectorize, random_forest sugar.

Mirrors reference dsl suites (RichTextFeatureTest parsePhone cases,
RichVectorFeatureTest idf, RichFeatureTest collect/filterNot).
"""
import numpy as np
import pytest

from transmogrifai_tpu.testkit import TestFeatureBuilder
from transmogrifai_tpu.transformers.text import parse_phone_e164
from transmogrifai_tpu.types import PickList, Real, RealNN, Text
from transmogrifai_tpu.workflow import Workflow


def _run(ds, *result_features):
    wf = Workflow().set_input_dataset(ds).set_result_features(*result_features)
    return wf.train().transform(ds)


class TestParsePhone:
    def test_e164_helper(self):
        assert parse_phone_e164("(555) 123-4567", "US") == "+15551234567"
        assert parse_phone_e164("+1 555 123 4567") == "+15551234567"
        # NANP national form carrying the country code
        assert parse_phone_e164("1-555-123-4567", "US") == "+15551234567"
        assert parse_phone_e164("garbage") is None
        assert parse_phone_e164("123") is None
        # GB trunk prefix stripped before the cc is applied
        out = parse_phone_e164("07911 123456", "GB")
        assert out is not None and out.startswith("+44") and "07911" not in out

    def test_dsl_stage(self):
        ds, (p,) = TestFeatureBuilder.build(
            ("p", Text, ["555-123-4567", "12", None]))
        parsed = p.parse_phone()
        out = _run(ds, parsed)
        col = out.column(parsed.name).data
        assert col[0] == "+15551234567"
        assert col[1] is None and col[2] is None


class TestIdf:
    def test_matches_spark_formula(self):
        docs = [["a", "b"], ["a"], ["a", "c"], []]
        ds, (t,) = TestFeatureBuilder.build(
            ("t", Text, [" ".join(d) for d in docs]))
        counts = t.tokenize().count_vectorize(vocab_size=8)
        scaled = counts.idf()
        out = _run(ds, counts, scaled)
        raw = out.column(counts.name).data
        got = out.column(scaled.name).data
        m = raw.shape[0]
        df = (raw > 0).sum(axis=0)
        expect = raw * np.log((m + 1.0) / (df + 1.0))[None, :]
        np.testing.assert_allclose(got, expect, rtol=1e-5)
        # idf passes metadata through untouched (count vectors carry none)
        md = out.column(scaled.name).metadata
        assert md is None or md.size == raw.shape[1]

    def test_min_doc_freq_zeroes(self):
        # df(a)=3 of m=3 (idf exactly 0 by the formula), df(b)=2, df(c)=1
        ds, (t,) = TestFeatureBuilder.build(
            ("t", Text, ["a b", "a b", "a c"]))
        counts = t.tokenize().count_vectorize(vocab_size=8)
        scaled = counts.idf(min_doc_freq=2)
        out = _run(ds, counts, scaled)
        raw = out.column(counts.name).data
        got = out.column(scaled.name).data
        df = (raw > 0).sum(axis=0)
        assert np.all(got[:, df < 2] == 0.0)
        # the df=2 column survives with idf log(4/3)
        keep = (df == 2)
        assert np.any(got[:, keep] != 0.0)


class TestDeindexCollect:
    def test_index_then_deindex_roundtrip(self):
        vals = ["red", "blue", "red", "green"]
        ds, (t,) = TestFeatureBuilder.build(("t", Text, vals))
        idx = t.index_string()
        # the indexer orders its vocabulary by frequency (Counter
        # .most_common, insertion-stable on ties) — mirror that ordering
        from collections import Counter
        labels = [w for w, _ in Counter(vals).most_common()]
        back = idx.deindexed(labels=labels)
        out = _run(ds, back)
        assert list(out.column(back.name).data) == vals

    def test_collect_and_filter_not(self):
        ds, (a,) = TestFeatureBuilder.build(("a", Real, [1.0, -2.0, 3.0]))
        pos = a.collect(lambda v: v * 10 if v > 0 else None, default=0.0)
        nn = a.filter_not(lambda v: v < 0, default=-99.0)
        out = _run(ds, pos, nn)
        np.testing.assert_allclose(out.column(pos.name).data, [10.0, 0.0, 30.0])
        np.testing.assert_allclose(out.column(nn.name).data, [1.0, -99.0, 3.0])


class TestVectorSugar:
    def test_smart_vectorize_two_texts(self):
        ds, (t1, t2) = TestFeatureBuilder.build(
            ("t1", Text, ["x", "y", "x", "y"]),
            ("t2", Text, ["p q", "r s", "p r", "q s"]))
        vec = t1.smart_vectorize(t2, max_cardinality=3, num_features=16)
        out = _run(ds, vec)
        assert out.column(vec.name).data.shape[0] == 4
        assert out.column(vec.name).data.shape[1] > 2

    def test_random_forest_sugar(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=80)
        y = (x > 0).astype(float)
        ds, (label, xf) = TestFeatureBuilder.build(
            ("label", RealNN, y.tolist()),
            ("x", Real, x.tolist()))
        vec = xf.vectorize()
        pred = vec.random_forest(label, num_trees=5, max_depth=3)
        out = _run(ds, pred)
        from transmogrifai_tpu.models.prediction import prediction_of
        preds = prediction_of(out.column(pred.name))
        assert (preds == y).mean() > 0.9
