"""VectorMetadata provenance laws (reference OpVectorMetadata /
OpVectorColumnMetadata, features/.../utils/spark/): naming, select/concat
algebra, JSON round-trip, and end-to-end provenance through transmogrify
— ModelInsights depends on every one of these invariants."""
import numpy as np

from transmogrifai_tpu.data.vector import VectorColumnMetadata, VectorMetadata


def _md(n=4, parent="f"):
    cols = [VectorColumnMetadata(parent_feature_name=parent,
                                 parent_feature_type="Real",
                                 grouping=None, indicator_value=None,
                                 descriptor_value=f"c{i}")
            for i in range(n)]
    return VectorMetadata(name="vec", columns=cols)


class TestAlgebra:
    def test_select_preserves_provenance(self):
        md = _md(5)
        sub = md.select([0, 2, 4])
        assert sub.size == 3
        assert all(c.parent_feature_name == "f" for c in sub.columns)

    def test_concat_sizes_and_order(self):
        a, b = _md(2, "a"), _md(3, "b")
        cat = VectorMetadata.concat("out", [a, b])
        assert cat.size == 5
        assert cat.parent_features()[:1] == ["a"]
        assert [c.parent_feature_name for c in cat.columns] == \
            ["a", "a", "b", "b", "b"]

    def test_json_round_trip(self):
        md = _md(3)
        md2 = VectorMetadata.from_json(md.to_json())
        assert md2.size == md.size
        assert md2.column_names() == md.column_names()

    def test_index_of(self):
        md = _md(3)
        names = md.column_names()
        for i, nm in enumerate(names):
            assert md.index_of(nm) == i


class TestEndToEndProvenance:
    def test_transmogrify_columns_trace_to_raw_features(self):
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        from transmogrifai_tpu.data.dataset import Dataset
        from transmogrifai_tpu.features.builder import FeatureBuilder
        from transmogrifai_tpu.types import PickList, Real
        from transmogrifai_tpu.workflow.workflow import Workflow

        rng = np.random.default_rng(0)
        n = 200
        ds = Dataset.from_features([
            ("age", Real, rng.uniform(1, 80, n).tolist()),
            ("cls", PickList, rng.choice(["a", "b", "c"], n).tolist()),
        ])
        fage = FeatureBuilder.Real("age").extract(
            lambda r: r.get("age")).as_predictor()
        fcls = FeatureBuilder.PickList("cls").extract(
            lambda r: r.get("cls")).as_predictor()
        vec = transmogrify([fage, fcls])
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(vec).train()
        col = model.transform(ds).column(vec.name)
        md = col.metadata
        # every output column traces to one of the two raw features
        assert set(md.parent_features()) <= {"age", "cls"}
        assert md.size == col.data.shape[1]
        # null indicators present and flagged
        nulls = [c for c in md.columns if c.is_null_indicator]
        assert nulls and all(c.parent_feature_name in ("age", "cls")
                             for c in nulls)
        # indicator (one-hot) columns carry their category value
        indicators = [c for c in md.columns
                      if c.indicator_value not in (None, "")]
        assert {c.indicator_value for c in indicators} >= {"a", "b"}
