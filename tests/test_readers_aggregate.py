"""Aggregate / conditional / joined readers.

Mirrors reference suites readers/src/test/.../DataReadersTest,
JoinedDataReaderDataTest: monoid aggregation per key with cutoff times,
two-pass conditional aggregation, key joins.
"""
import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.readers.readers import (
    AggregateReader, ConditionalReader, JoinedReader, ListReader, KEY_COLUMN)


EVENTS = [
    # user, t, amount, kind
    {"user": "a", "t": 1, "amount": 10.0, "kind": "buy"},
    {"user": "a", "t": 2, "amount": 5.0, "kind": "view"},
    {"user": "a", "t": 9, "amount": 100.0, "kind": "buy"},   # after cutoff
    {"user": "b", "t": 3, "amount": 7.0, "kind": "view"},
    {"user": "b", "t": 4, "amount": 3.0, "kind": "buy"},
    {"user": "b", "t": 6, "amount": 2.0, "kind": "view"},
]


def _features(cutoff_response=False):
    amount = FeatureBuilder.Real("amount").extract(
        lambda r: r.get("amount")).aggregate("sum").as_predictor()
    last_kind = FeatureBuilder.PickList("kind").extract(
        lambda r: r.get("kind")).aggregate("last").as_predictor()
    return amount, last_kind


class TestAggregateReader:
    def test_sum_and_last_with_cutoff(self):
        amount, last_kind = _features()
        reader = AggregateReader(ListReader(EVENTS),
                                 key_fn=lambda r: r["user"],
                                 cutoff_time=8,
                                 event_time_fn=lambda r: r["t"])
        ds = reader.generate_dataset([amount, last_kind])
        assert ds.n_rows == 2  # one row per user
        keys = list(ds.column(KEY_COLUMN).data)
        i_a, i_b = keys.index("a"), keys.index("b")
        # events at t>=8 excluded for predictors
        assert ds.column("amount").data[i_a] == pytest.approx(15.0)
        assert ds.column("amount").data[i_b] == pytest.approx(12.0)
        assert ds.column("kind").data[i_a] == "view"   # last before cutoff
        assert ds.column("kind").data[i_b] == "view"


class TestConditionalReader:
    def test_predictors_before_responses_after_condition(self):
        # condition: first 'buy' event sets the per-key clock
        amount = FeatureBuilder.Real("amount").extract(
            lambda r: r.get("amount")).aggregate("sum").as_predictor()
        spent_after = FeatureBuilder.Real("amount").extract(
            lambda r: r.get("amount")).aggregate("sum").as_response()
        spent_after = FeatureBuilder.RealNN("after").extract(
            lambda r: r.get("amount")).aggregate("sum").as_response()
        reader = ConditionalReader(
            ListReader(EVENTS), key_fn=lambda r: r["user"],
            condition_fn=lambda r: r["kind"] == "buy",
            event_time_fn=lambda r: r["t"])
        ds = reader.generate_dataset([amount, spent_after])
        keys = list(ds.column(KEY_COLUMN).data)
        i_a, i_b = keys.index("a"), keys.index("b")
        # user a: first buy at t=1 -> predictors strictly before t=1:
        # none (reference keeps date < cutoff, FeatureAggregator.scala:120)
        assert np.isnan(ds.column("amount").data[i_a])
        # responses at/after t=1: 10 + 5 + 100
        assert ds.column("after").data[i_a] == pytest.approx(115.0)
        # user b: first buy at t=4 -> predictor t=3 only; responses 3 + 2
        assert ds.column("amount").data[i_b] == pytest.approx(7.0)
        assert ds.column("after").data[i_b] == pytest.approx(5.0)

    def test_drop_keys_without_condition(self):
        events = EVENTS + [{"user": "c", "t": 1, "amount": 1.0,
                            "kind": "view"}]
        amount = FeatureBuilder.Real("amount").extract(
            lambda r: r.get("amount")).aggregate("sum").as_predictor()
        reader = ConditionalReader(
            ListReader(events), key_fn=lambda r: r["user"],
            condition_fn=lambda r: r["kind"] == "buy",
            event_time_fn=lambda r: r["t"])
        ds = reader.generate_dataset([amount])
        assert "c" not in set(ds.column(KEY_COLUMN).data)


class TestJoinedReader:
    def test_key_join(self):
        users = [{"uid": "a", "plan": "pro"}, {"uid": "b", "plan": "free"}]
        plan = FeatureBuilder.PickList("plan").extract(
            lambda r: r.get("plan")).as_predictor()
        amount, _ = _features()
        left = AggregateReader(ListReader(EVENTS),
                               key_fn=lambda r: r["user"],
                               event_time_fn=lambda r: r["t"])
        right = ListReader(users, key_fn=lambda r: r["uid"])
        joined = JoinedReader(left, right,
                              left_features=["amount"],
                              right_features=["plan"])
        ds = joined.generate_dataset([amount, plan])
        assert ds.n_rows == 2
        keys = list(ds.column(KEY_COLUMN).data)
        i_a = keys.index("a")
        assert ds.column("plan").data[i_a] == "pro"
        assert ds.column("amount").data[i_a] == pytest.approx(115.0)
        i_b = keys.index("b")
        assert ds.column("plan").data[i_b] == "free"
