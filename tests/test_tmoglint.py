"""tmoglint: fixture-driven rule tests + baseline freshness + the f32
embeddings tolerance contract (ops/embeddings.py dtype fix, TPU003).

Every rule family has known-bad snippets (must be caught) and known-good
snippets (must stay silent) so rule precision is pinned, not aspirational.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tools.tmoglint.baseline import diff_baseline, load_baseline
from tools.tmoglint.core import LintContext, run_rules, scan_paths

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src: str, path: str = "ops/mod.py", rules=None):
    ctx = LintContext(path, textwrap.dedent(src))
    return run_rules([ctx], only=rules)


def lint_many(named_srcs, rules=None):
    ctxs = [LintContext(p, textwrap.dedent(s)) for p, s in named_srcs]
    return run_rules(ctxs, only=rules)


def rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- TPU001: host sync in hot path ------------------------------------------

class TestTPU001:
    def test_item_in_jitted_fn(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
        """, rules=["TPU001"])
        assert len(out) == 1 and out[0].rule == "TPU001"
        assert ".item()" in out[0].message

    def test_np_asarray_in_scan_body(self):
        out = lint("""
            import jax
            import numpy as np

            def step(c, x):
                return c, np.asarray(x)

            def run(xs):
                return jax.lax.scan(step, 0, xs)
        """, rules=["TPU001"])
        assert rule_lines(out, "TPU001"), "np.asarray in scan body missed"

    def test_float_cast_of_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """, rules=["TPU001"])
        assert len(out) == 1

    def test_block_until_ready_reachable_through_call(self):
        """Hazards in helpers *called from* jitted code are still caught."""
        out = lint("""
            import jax

            def helper(x):
                return x.block_until_ready()

            @jax.jit
            def f(x):
                return helper(x)
        """, rules=["TPU001"])
        assert len(out) == 1

    def test_negative_host_code_untouched(self):
        """The same constructs outside any trace are fine."""
        out = lint("""
            import numpy as np

            def host_fn(x):
                arr = np.asarray(x)
                return float(arr.sum()), arr.tolist()
        """, rules=["TPU001"])
        assert out == []

    def test_negative_scalar_annotated_param(self):
        """float() of a python-scalar-annotated param is static config."""
        out = lint("""
            import jax

            @jax.jit
            def f(x, frac: float = 0.5):
                k = int(round(frac * 8))
                return x * k
        """, rules=["TPU001"])
        assert out == []


# -- TPU002: recompile hazards ----------------------------------------------

class TestTPU002:
    def test_branch_on_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, rules=["TPU002"])
        assert len(out) == 1 and "if" in out[0].message

    def test_static_argnames_typo(self):
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n_binz",))
            def f(x, n_bins):
                return x * n_bins
        """, rules=["TPU002"])
        assert len(out) == 1 and "n_binz" in out[0].message

    def test_fstring_of_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                name = f"val={x}"
                return x
        """, rules=["TPU002"])
        assert len(out) == 1

    def test_print_under_trace(self):
        out = lint("""
            import jax

            def body(c, x):
                print("step")
                return c, x

            def run(xs):
                return jax.lax.scan(body, 0, xs)
        """, rules=["TPU002"])
        assert len(out) == 1 and "print" in out[0].message

    def test_array_annotated_static_arg(self):
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("tbl",))
            def f(x, tbl: jax.Array):
                return x
        """, rules=["TPU002"])
        assert len(out) == 1 and "unhashable" in out[0].message

    def test_negative_none_check_and_static_branch(self):
        """`x is None` is static; branches on static args are static;
        branches on shapes are static."""
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("standardize",))
            def f(x, w=None, standardize=True):
                if w is None:
                    w = x * 0 + 1
                if standardize:
                    x = x / 2
                if x.shape[0] > 4:
                    x = x[:4]
                return x * w
        """, rules=["TPU002"])
        assert out == []


# -- TPU003: dtype drift -----------------------------------------------------

class TestTPU003:
    def test_np_float64_in_ops(self):
        out = lint("""
            import numpy as np

            def acc(n):
                return np.zeros((n, n), np.float64)
        """, path="ops/kern.py", rules=["TPU003"])
        assert len(out) == 1 and "float64" in out[0].message

    def test_dtypeless_jnp_zeros_in_ops(self):
        out = lint("""
            import jax.numpy as jnp

            def buf(n):
                return jnp.zeros((n, 8))
        """, path="ops/kern.py", rules=["TPU003"])
        assert len(out) == 1 and "dtype-less" in out[0].message

    def test_negative_outside_kernel_path(self):
        """float64 on a non-ops host path is not TPU003's business."""
        out = lint("""
            import numpy as np

            def acc(n):
                return np.zeros((n, n), np.float64)
        """, path="readers/csv.py", rules=["TPU003"])
        assert out == []

    def test_negative_explicit_dtype_and_asarray(self):
        out = lint("""
            import jax.numpy as jnp

            def buf(x, n):
                a = jnp.zeros((n, 8), jnp.float32)
                b = jnp.asarray(x)  # cast preserves dtype: not a creation
                return a, b
        """, path="ops/kern.py", rules=["TPU003"])
        assert out == []

    def test_suppression_same_line_and_above(self):
        out = lint("""
            import numpy as np

            def acc(n):
                a = np.zeros(n, np.float64)  # tmoglint: disable=TPU003  ABI
                # tmoglint: disable=TPU003  host precision only
                b = np.zeros(n, np.float64)
                return a, b
        """, path="ops/kern.py", rules=["TPU003"])
        assert out == []


# -- TPU004: tracer leak -----------------------------------------------------

class TestTPU004:
    def test_self_assign_in_jitted_method(self):
        out = lint("""
            import jax

            class Model:
                @jax.jit
                def f(self, x):
                    self.cache = x
                    return x
        """, rules=["TPU004"])
        assert len(out) == 1 and "self.cache" in out[0].message

    def test_global_in_scan_body(self):
        out = lint("""
            import jax

            def body(c, x):
                global LAST
                LAST = x
                return c, x

            def run(xs):
                return jax.lax.scan(body, 0, xs)
        """, rules=["TPU004"])
        assert rule_lines(out, "TPU004"), "global stmt under trace missed"

    def test_negative_self_assign_outside_trace(self):
        out = lint("""
            class Model:
                def fit(self, x):
                    self.cache = x
                    return self
        """, rules=["TPU004"])
        assert out == []


# -- TPU005: unsynced wall timing --------------------------------------------

class TestTPU005:
    def test_jnp_call_in_timed_window(self):
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and out[0].rule == "TPU005"
        assert "block_until_ready" in out[0].message

    def test_locally_jitted_name_in_window(self):
        out = lint("""
            import time
            import jax

            f = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.time()
                y = f(x)
                dt = time.time() - t0
                return dt
        """, rules=["TPU005"])
        assert len(out) == 1 and "`f`" in out[0].message

    def test_dispatch_hint_validate(self):
        out = lint("""
            import time

            def sweep(val, X, y):
                t0 = time.perf_counter()
                best = val.validate([(est, grids)], X, y)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and "val.validate" in out[0].message

    def test_negative_block_until_ready_present(self):
        out = lint("""
            import time
            import jax
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                jax.block_until_ready(y)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_negative_host_only_timing(self):
        out = lint("""
            import time
            import numpy as np

            def bench(a, b):
                t0 = time.perf_counter()
                y = np.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_negative_dispatch_outside_window(self):
        """A jax call BEFORE the anchor is not what the delta times."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                y = jnp.dot(a, b)
                t0 = time.perf_counter()
                s = sum(range(100))
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_suppression_with_justification(self):
        out = lint("""
            import time

            def sweep(val, X, y):
                t0 = time.perf_counter()
                best = val.validate([(est, grids)], X, y)
                # tmoglint: disable=TPU005  validate returns host floats
                dt = time.perf_counter() - t0
                return dt
        """, rules=["TPU005"])
        assert out == []

    def test_bare_time_import_idiom(self):
        """`from time import time` — bare time() deltas count too."""
        out = lint("""
            from time import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time()
                y = jnp.dot(a, b)
                return time() - t0
        """, rules=["TPU005"])
        assert len(out) == 1

    def test_aliased_jax_numpy_import_is_dispatchish(self):
        """`import jax.numpy as jnumpy` resolves through jnp_aliases
        (like TPU003) — aliasing must not dodge the rule."""
        out = lint("""
            import time
            import jax.numpy as jnumpy

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnumpy.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and "jnumpy.dot" in out[0].message

    def test_two_anchor_idiom_covers_the_work_between(self):
        """`t0=..; dispatch; t1=..; dt = t1 - t0` — the window spans from
        the EARLIEST anchor in the delta, so the dispatch between the two
        anchors is covered."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                t1 = time.perf_counter()
                dt = t1 - t0
                return dt, y
        """, rules=["TPU005"])
        assert len(out) == 1 and "jnp.dot" in out[0].message

    def test_negative_dispatch_between_two_host_windows(self):
        """A dispatch call BETWEEN two disjoint host-only timed windows is
        untimed: each delta pairs with its own (latest) anchor, windows
        must not merge."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                s1 = sum(range(100))
                d1 = time.perf_counter() - t0
                y = jnp.dot(a, b)
                t0 = time.perf_counter()
                s2 = sum(range(100))
                d2 = time.perf_counter() - t0
                return d1, d2, y
        """, rules=["TPU005"])
        assert out == []

    def test_anchor_reassignment_scopes_each_window(self):
        """Same anchor name reused: only the window whose own span holds
        the dispatch call fires, anchored at THAT delta."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                s1 = sum(range(100))
                d1 = time.perf_counter() - t0
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                d2 = time.perf_counter() - t0
                return d1, d2, y
        """, rules=["TPU005"])
        assert len(out) == 1
        # the finding anchors at d2's line, not d1's
        assert out[0].snippet.startswith("d2")


# -- THR001: shared-mutable-state races --------------------------------------

class TestTHR001:
    def test_thread_written_attr_read_unlocked(self):
        out = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self.count += 1

                def snapshot(self):
                    return self.count
        """, rules=["THR001"])
        assert len(out) == 1 and out[0].rule == "THR001"
        assert "Worker.count" in out[0].message
        assert "lock" in out[0].message

    def test_http_handler_attr_unlocked(self):
        """ThreadingHTTPServer handlers run one thread per connection:
        an unlocked counter on the handler class races with itself."""
        out = lint("""
            from http.server import BaseHTTPRequestHandler

            class H(BaseHTTPRequestHandler):
                hits = 0

                def do_GET(self):
                    self.hits = self.hits + 1

                def metrics(self):
                    return self.hits
        """, rules=["THR001"])
        assert rule_lines(out, "THR001"), "handler-thread race missed"

    def test_negative_common_lock_both_sides(self):
        out = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0
                    threading.Thread(target=self._run).start()

                def _run(self):
                    with self._lock:
                        self.count += 1

                def snapshot(self):
                    with self._lock:
                        return self.count
        """, rules=["THR001"])
        assert out == []

    def test_negative_init_only_attr_is_config(self):
        """Attributes only written in __init__ are immutable config —
        reads from any thread are fine."""
        out = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.size = 8
                    threading.Thread(target=self._run).start()

                def _run(self):
                    return self.size * 2
        """, rules=["THR001"])
        assert out == []

    def test_private_helper_inherits_caller_lock(self):
        """A private helper whose EVERY call site holds the lock is
        effectively locked — the `_close_window` pattern must not
        flag."""
        out = lint("""
            import threading

            class Window:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = 0
                    threading.Thread(target=self.tick).start()

                def tick(self):
                    with self._lock:
                        self._advance()

                def _advance(self):
                    self.rows += 1

                def read(self):
                    with self._lock:
                        return self.rows
        """, rules=["THR001"])
        assert out == []

    def test_suppression_with_justification(self):
        out = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0
                    threading.Thread(target=self._run).start()

                def _run(self):
                    # tmoglint: disable=THR001  read happens-after join
                    self.count += 1

                def snapshot(self):
                    return self.count
        """, rules=["THR001"])
        assert out == []

    def test_negative_shared_lock_across_classes(self):
        """ONE lock object passed into two collaborating classes (the
        fleet pattern): `self.lock = lock or RLock()` registers a SHARED
        lock whose identity canonicalizes by name, so a write under
        Owner A's alias and a read under Owner B's alias intersect."""
        out = lint("""
            import threading

            class Handle:
                def __init__(self):
                    self.port = 0

            class Supervisor:
                def __init__(self, handle: Handle, lock=None):
                    self.lock = lock or threading.RLock()
                    self.handle = handle
                    threading.Thread(target=self._watch).start()

                def _watch(self):
                    with self.lock:
                        self.handle.port = 99

            class Router:
                def __init__(self, handle: Handle, lock=None):
                    self.lock = lock or threading.RLock()
                    self.handle = handle

                def pick(self):
                    with self.lock:
                        return self.handle.port
        """, rules=["THR001"])
        assert rule_lines(out, "THR001") == []

    def test_shared_lock_does_not_blind_unlocked_side(self):
        """The shared-lock alias must not exempt a genuinely unlocked
        access: same shape as above but the reader takes no lock."""
        out = lint("""
            import threading

            class Handle:
                def __init__(self):
                    self.port = 0

            class Supervisor:
                def __init__(self, handle: Handle, lock=None):
                    self.lock = lock or threading.RLock()
                    self.handle = handle
                    threading.Thread(target=self._watch).start()

                def _watch(self):
                    with self.lock:
                        self.handle.port = 99

            class Router:
                def __init__(self, handle: Handle, lock=None):
                    self.lock = lock or threading.RLock()
                    self.handle = handle

                def pick(self):
                    return self.handle.port

                def use(self):
                    t = threading.Thread(target=self.pick)
                    t.start()
        """, rules=["THR001"])
        assert rule_lines(out, "THR001"), "unlocked reader side missed"

    def test_shared_lock_is_one_thr003_node(self):
        """Two classes aliasing ONE shared lock and calling into each
        other while holding it read, pre-canonicalization, as
        `A.lock -> B.lock` plus `B.lock -> A.lock` — a bogus inversion.
        It is one reentrant lock: no cycle."""
        out = lint("""
            import threading

            class A:
                def __init__(self, lock, b):
                    self.lock = lock or threading.RLock()
                    self.b = b

                def enter_a(self):
                    with self.lock:
                        self.b.leaf_b()

                def leaf_a(self):
                    with self.lock:
                        pass

            class B:
                def __init__(self, lock, a):
                    self.lock = lock or threading.RLock()
                    self.a = a

                def enter_b(self):
                    with self.lock:
                        self.a.leaf_a()

                def leaf_b(self):
                    with self.lock:
                        pass
        """, rules=["THR003"])
        assert rule_lines(out, "THR003") == []

class TestTHR002:
    def test_sleep_under_lock(self):
        out = lint("""
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def poll(self):
                    with self._lock:
                        time.sleep(0.1)
        """, rules=["THR002"])
        assert len(out) == 1 and "time.sleep" in out[0].message

    def test_blocking_queue_get_under_lock(self):
        out = lint("""
            import queue
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        return self._q.get()
        """, rules=["THR002"])
        assert len(out) == 1 and "queue.get" in out[0].message

    def test_device_fetch_of_jitted_state_under_lock(self):
        """np.asarray of an attr assigned from a jitted call is a D2H
        sync — the monitor window-close pattern."""
        out = lint("""
            import threading

            import jax
            import numpy as np

            @jax.jit
            def _step(s):
                return s + 1

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = None

                def observe(self):
                    with self._lock:
                        self.state = _step(self.state)

                def close(self):
                    with self._lock:
                        return np.asarray(self.state)
        """, rules=["THR002"])
        assert len(out) == 1 and "device-resident" in out[0].message

    def test_negative_async_dispatch_under_lock_ok(self):
        """Dispatch is async — only WAITING under a lock is flagged."""
        out = lint("""
            import threading

            import jax

            @jax.jit
            def _step(s, x):
                return s + x

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = 0

                def observe(self, x):
                    with self._lock:
                        self.state = _step(self.state, x)
        """, rules=["THR002"])
        assert out == []

    def test_negative_nonblocking_queue_get(self):
        out = lint("""
            import queue
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        return self._q.get(block=False)
        """, rules=["THR002"])
        assert out == []


# -- THR003: lock-order inversion --------------------------------------------

class TestTHR003:
    def test_lexical_inversion(self):
        out = lint("""
            import threading

            class A:
                def __init__(self):
                    self.l1 = threading.Lock()
                    self.l2 = threading.Lock()

                def f(self):
                    with self.l1:
                        with self.l2:
                            pass

                def g(self):
                    with self.l2:
                        with self.l1:
                            pass
        """, rules=["THR003"])
        assert len(out) >= 1 and out[0].rule == "THR003"
        assert "inversion" in out[0].message

    def test_inversion_through_a_call(self):
        """f holds l1 and calls h (which takes l2); g holds l2 and
        calls k (which takes l1): the cycle spans the call graph."""
        out = lint("""
            import threading

            class A:
                def __init__(self):
                    self.l1 = threading.Lock()
                    self.l2 = threading.Lock()

                def f(self):
                    with self.l1:
                        self.h()

                def h(self):
                    with self.l2:
                        pass

                def g(self):
                    with self.l2:
                        self.k()

                def k(self):
                    with self.l1:
                        pass
        """, rules=["THR003"])
        assert len(out) >= 1 and out[0].rule == "THR003"

    def test_negative_consistent_global_order(self):
        out = lint("""
            import threading

            class A:
                def __init__(self):
                    self.l1 = threading.Lock()
                    self.l2 = threading.Lock()

                def f(self):
                    with self.l1:
                        with self.l2:
                            pass

                def g(self):
                    with self.l1:
                        with self.l2:
                            pass
        """, rules=["THR003"])
        assert out == []


# -- THR004: Condition / Event misuse ----------------------------------------

class TestTHR004:
    def test_notify_without_holding(self):
        out = lint("""
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def wake(self):
                    self._cond.notify()
        """, rules=["THR004"])
        assert len(out) == 1 and "without holding" in out[0].message

    def test_wait_while_holding_second_lock(self):
        out = lint("""
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._lock = threading.Lock()

                def pause(self):
                    with self._lock:
                        with self._cond:
                            self._cond.wait()
        """, rules=["THR004"])
        assert len(out) == 1 and "ALSO holding" in out[0].message

    def test_with_on_event(self):
        out = lint("""
            import threading

            class W:
                def __init__(self):
                    self._done = threading.Event()

                def finish(self):
                    with self._done:
                        pass
        """, rules=["THR004"])
        assert len(out) == 1 and "Event" in out[0].message

    def test_negative_proper_condition_discipline(self):
        out = lint("""
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def pause(self):
                    with self._cond:
                        self._cond.wait(0.1)

                def wake(self):
                    with self._cond:
                        self._cond.notify_all()
        """, rules=["THR004"])
        assert out == []


# -- BUF001: use-after-donate ------------------------------------------------

class TestBUF001:
    def test_read_after_donating_call(self):
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                return c.sum()
        """, rules=["BUF001"])
        assert len(out) == 1 and "donated" in out[0].message
        assert "rebind" in out[0].message

    def test_donated_in_loop_without_rebind(self):
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                for x in xs:
                    step(c, x)
        """, rules=["BUF001"])
        assert len(out) == 1 and "loop" in out[0].message

    def test_self_attr_read_after_donation(self):
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            class W:
                def fold(self, x):
                    out = step(self.state, x)
                    return self.state.sum()
        """, rules=["BUF001"])
        assert len(out) == 1 and "self.state" in out[0].message

    def test_negative_rebind_idiom(self):
        """`c = step(c, x)` is THE sanctioned carry idiom."""
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                for x in xs:
                    c = step(c, x)
                return c.sum()
        """, rules=["BUF001"])
        assert out == []

    def test_negative_metadata_reads_survive_donation(self):
        """.shape/.dtype stay valid on a deleted array."""
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                return out, c.shape, c.dtype
        """, rules=["BUF001"])
        assert out == []


# -- BUF002: donation coverage -----------------------------------------------

class TestBUF002:
    def test_loop_carry_through_undonated_step(self):
        out = lint("""
            import jax

            @jax.jit
            def step(acc, t):
                return acc + t

            def run(acc, tiles):
                for t in tiles:
                    acc = step(acc, t)
                return acc
        """, rules=["BUF002"])
        assert len(out) == 1 and "does not donate" in out[0].message

    def test_attr_state_through_undonated_step(self):
        """An attribute is loop-carried across calls by construction —
        the ServeMonitor sketch-state regression class."""
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("bins",))
            def sketch(state, X, bins):
                return state + X.sum()

            class Mon:
                def observe(self, X):
                    self.state = sketch(self.state, X, bins=8)
        """, rules=["BUF002"])
        assert len(out) == 1 and "self.state" in out[0].message

    def test_negative_donated_step(self):
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(acc, t):
                return acc + t

            def run(acc, tiles):
                for t in tiles:
                    acc = step(acc, t)
                return acc
        """, rules=["BUF002"])
        assert out == []

    def test_negative_non_carry_rebind(self):
        """y = step(x, t) rebinding a DIFFERENT name is not a carry."""
        out = lint("""
            import jax

            @jax.jit
            def step(acc, t):
                return acc + t

            def run(x, tiles):
                for t in tiles:
                    y = step(x, t)
                return x
        """, rules=["BUF002"])
        assert out == []

    def test_suppression(self):
        out = lint("""
            import jax

            @jax.jit
            def step(acc, t):
                return acc + t

            def run(acc, tiles):
                for t in tiles:
                    # tmoglint: disable=BUF002  acc aliases a checkpoint
                    acc = step(acc, t)
                return acc
        """, rules=["BUF002"])
        assert out == []


# -- BUF003: donated buffer into spans/events --------------------------------

class TestBUF003:
    def test_event_captures_donated_buffer(self):
        out = lint("""
            import functools

            import jax

            from transmogrifai_tpu.utils.metrics import collector

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                collector.event("pass_done", state=c)
                return out
        """, rules=["BUF003"])
        assert len(out) == 1 and "span/event/log" in out[0].message

    def test_log_captures_donated_buffer(self):
        out = lint("""
            import functools
            import logging

            import jax

            _log = logging.getLogger(__name__)

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                _log.info("carry was %s", c)
                return out
        """, rules=["BUF003"])
        assert len(out) == 1

    def test_negative_logging_the_rebound_result(self):
        out = lint("""
            import functools

            import jax

            from transmogrifai_tpu.utils.metrics import collector

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                collector.event("pass_start", rows=int(c.shape[0]))
                c = step(c, xs)
                collector.event("pass_done", state=c)
                return c
        """, rules=["BUF003"])
        assert out == []


# -- DAG001: stage contracts -------------------------------------------------

MINI_TYPES = ("pkg/types.py", """
    class FeatureType:
        pass

    class Real(FeatureType):
        pass

    class Text(FeatureType):
        pass
""")


class TestDAG001:
    def test_missing_input_types(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class MyStage(Transformer):
                output_type = Real
        """)], rules=["DAG001"])
        assert len(out) == 1 and "input_types" in out[0].message

    def test_unknown_feature_type_in_contract(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class Widget:
                pass

            class MyStage(Transformer):
                input_types = (Widget,)
                output_type = Real
        """)], rules=["DAG001"])
        assert len(out) == 1 and "Widget" in out[0].message

    def test_set_input_arity_mismatch(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Real)
                output_type = Real
        """), ("pkg/dsl.py", """
            def wire(a):
                return TwoIn().set_input(a).get_output()
        """)], rules=["DAG001"])
        assert len(out) == 1 and "1 input(s)" in out[0].message

    def test_starred_wiring_of_non_sequence_stage(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Real)
                output_type = Real
                is_sequence = False
        """), ("pkg/dsl.py", """
            def wire(feats):
                return TwoIn().set_input(*feats)
        """)], rules=["DAG001"])
        assert len(out) == 1 and "sequence" in out[0].message

    def test_negative_well_formed_stage_and_wiring(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Text)
                output_type = Real

            class SeqStage(Transformer):
                input_types = (Real,)
                output_type = Real
                is_sequence = True
        """), ("pkg/dsl.py", """
            def wire(a, b, feats):
                x = TwoIn().set_input(a, b).get_output()
                y = SeqStage().set_input(*feats).get_output()
                return x, y
        """)], rules=["DAG001"])
        assert out == []

    def test_negative_dynamic_output_type_binding(self):
        """Passthrough stages that pin output_type per-wiring (in
        set_input) are declared-enough."""
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class Passthrough(Transformer):
                input_types = (Real,)

                def set_input(self, *features):
                    out = super().set_input(*features)
                    self.output_type = features[0].feature_type
                    return out
        """)], rules=["DAG001"])
        assert out == []


# -- real-repo guarantees ----------------------------------------------------

class TestRepoScan:
    @pytest.fixture(scope="class")
    def repo_findings(self):
        ctxs, errors = scan_paths(["transmogrifai_tpu", "tests"], REPO_ROOT)
        return errors + run_rules(ctxs)

    def test_baseline_is_fresh(self, repo_findings):
        """The committed baseline must match a fresh scan exactly: no new
        findings (undeclared debt) and no stale entries (fixed debt whose
        ledger line was never removed)."""
        baseline = load_baseline(
            os.path.join(REPO_ROOT, "tools", "tmoglint", "baseline.json"))
        new, stale = diff_baseline(repo_findings, baseline)
        assert not new, "\n".join(f.render() for f in new)
        assert not stale, f"stale baseline entries: {stale}"

    def test_no_syntax_errors_in_repo(self, repo_findings):
        assert not [f for f in repo_findings if f.rule == "SYNTAX"]


class TestCLI:
    def test_json_report_shape_and_exit_codes(self, tmp_path):
        bad = tmp_path / "ops"
        bad.mkdir()
        (bad / "kern.py").write_text(textwrap.dedent("""
            import numpy as np

            def acc(n):
                return np.zeros(n, np.float64)
        """))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--no-baseline", "--format", "json"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["total_findings"] == 1
        assert report["counts_by_rule"] == {"TPU003": 1}
        assert report["new"][0]["rule"] == "TPU003"
        assert report["ok"] is False
        # writing a baseline makes the same scan green
        base = tmp_path / "base.json"
        proc2 = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--baseline", str(base),
             "--write-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc2.returncode == 0
        proc3 = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--baseline", str(base)],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc3.returncode == 0, proc3.stdout + proc3.stderr

    def test_write_baseline_with_rule_filter_refused(self, tmp_path):
        """A rule-filtered scan must never overwrite the full baseline."""
        (tmp_path / "clean.py").write_text("x = 1\n")
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--baseline",
             str(tmp_path / "b.json"), "--rules", "TPU003",
             "--write-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 2
        assert "truncate" in proc.stderr
        assert not (tmp_path / "b.json").exists()

    def test_rules_family_prefix_selection(self, tmp_path):
        """--rules THR,BUF expands to the full families (the ISSUE's
        spelling) and composes with the stale-entry scoping guard: a
        baselined TPU entry is out of scope for a THR,BUF scan, so it
        is neither new nor stale."""
        (tmp_path / "mod.py").write_text(textwrap.dedent("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                return c.sum()
        """))
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"version": 1, "findings": [
            {"fingerprint": "feedfeedfeedfeed", "rule": "TPU003",
             "path": "other.py", "line": 1, "col": 0,
             "message": "unrelated grandfathered debt", "snippet": ""}]}))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "mod.py",
             "--root", str(tmp_path), "--baseline", str(base),
             "--rules", "THR,BUF", "--format", "json"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        # the family expanded to all seven new rules
        assert report["rules"] == ["BUF001", "BUF002", "BUF003",
                                   "THR001", "THR002", "THR003",
                                   "THR004"]
        assert report["counts_by_rule"] == {"BUF001": 1}
        # the TPU003 baseline entry is OUT of scope: not stale
        assert report["stale_baseline_entries"] == []
        assert report["new"][0]["rule"] == "BUF001"

    def test_unknown_rule_family_is_usage_error(self, tmp_path):
        (tmp_path / "clean.py").write_text("x = 1\n")
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--no-baseline",
             "--rules", "ZZZ9"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_no_files_is_usage_error(self, tmp_path):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "missing_dir",
             "--root", str(tmp_path), "--no-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 2
        assert "no .py files" in proc.stderr

    def test_stats_line_and_json_stats(self, tmp_path):
        (tmp_path / "clean.py").write_text("x = 1\n")
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--no-baseline", "--stats"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "tmoglint --stats:" in proc.stdout
        proc2 = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--no-baseline",
             "--format", "json"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        report = json.loads(proc2.stdout)
        stats = report["stats"]
        for key in ("files", "jobs", "parse_s", "file_rules_s",
                    "project_rules_s", "total_s"):
            assert key in stats, stats
        assert stats["files"] == 1

    def test_parallel_jobs_match_serial(self, tmp_path):
        """--jobs 2 and --jobs 1 must produce identical findings (the
        pool only changes WHO runs the per-file rules)."""
        (tmp_path / "ops").mkdir()
        (tmp_path / "ops" / "kern.py").write_text(textwrap.dedent("""
            import numpy as np

            def acc(n):
                return np.zeros(n, np.float64)
        """))
        (tmp_path / "host.py").write_text(textwrap.dedent("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(c, x):
                return c + x

            def run(c, xs):
                out = step(c, xs)
                return c.sum()
        """))
        (tmp_path / "a.py").write_text("x = 1\n")
        (tmp_path / "b.py").write_text("y = 2\n")
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        outs = []
        for jobs in ("1", "2"):
            proc = subprocess.run(
                [sys.executable, "-m", "tools.tmoglint", ".",
                 "--root", str(tmp_path), "--no-baseline",
                 "--jobs", jobs, "--format", "json"],
                cwd=REPO_ROOT, env=env, capture_output=True, text=True)
            assert proc.returncode == 1, proc.stdout + proc.stderr
            report = json.loads(proc.stdout)
            outs.append([(f["rule"], f["path"], f["fingerprint"])
                         for f in report["new"]])
        assert outs[0] == outs[1]
        assert {r for r, _, _ in outs[0]} == {"TPU003", "BUF001"}

    def test_stale_baseline_fails(self, tmp_path):
        """Fixing debt without regenerating the baseline must go red."""
        (tmp_path / "clean.py").write_text("x = 1\n")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"version": 1, "findings": [
            {"fingerprint": "deadbeefdeadbeef", "rule": "TPU003",
             "path": "gone.py", "line": 1, "col": 0,
             "message": "old debt", "snippet": ""}]}))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--baseline", str(base)],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 1
        assert "stale" in proc.stdout


# -- fitted models inherit their estimator's contract ------------------------

class TestFitPinsContract:
    def test_onehot_model_enforces_estimator_types(self):
        """OneHotModel's class contract is (None,) = any, but Estimator.fit
        pins each fitted instance to its estimator's concrete contract."""
        from transmogrifai_tpu.automl.vectorizers.categorical import (
            OneHotVectorizer)
        from transmogrifai_tpu.data.dataset import Dataset
        from transmogrifai_tpu.features.builder import FeatureBuilder
        from transmogrifai_tpu.types import PickList, Real

        rows = [{"cab": c, "age": float(i)}
                for i, c in enumerate(["A", "B", "A", "C"])]
        resp, feats = FeatureBuilder.from_rows(
            rows + [{"cab": "A", "age": 1.0, "y": 0.0}], response="y")
        cab = [f for f in feats if f.name == "cab"][0]
        age = [f for f in feats if f.name == "age"][0]
        assert cab.feature_type is PickList

        est = OneHotVectorizer(top_k=3).set_input(cab)
        ds = Dataset.from_rows(rows, [cab, age]) if \
            hasattr(Dataset, "from_rows") else None
        if ds is None:
            import transmogrifai_tpu.readers.readers as R
            ds = R.ListReader(rows).generate_dataset([cab, age])
        model = est.fit(ds)
        assert model.input_types == est.input_types
        with pytest.raises(TypeError):
            model.set_input(age)  # Real into a Text-pinned fitted pivot

        # the pin must survive a save/load round trip (registry path)
        from transmogrifai_tpu.stages.registry import build_stage
        args = json.loads(json.dumps(model.save_args()))
        rebuilt = build_stage(type(model).__name__, args)
        assert rebuilt.input_types == est.input_types
        with pytest.raises(TypeError):
            rebuilt.set_input(age)


# -- ops/embeddings.py f32 fix (TPU003 satellite) ----------------------------

class TestEmbeddingsF32:
    def test_cooccurrence_counts_exact_in_f32(self):
        from transmogrifai_tpu.ops.embeddings import cooccurrence_matrix
        docs = [["a", "b", "c", "a"], ["b", "c"], None, ["a"]] * 50
        C = cooccurrence_matrix(docs, vocab_bins=16, window=3)
        assert C.dtype == np.float32
        # windowed counts are small integers: f32 must hold them exactly
        assert np.array_equal(C, np.round(C))
        assert np.allclose(C, C.T)

    def test_mean_pool_f32_matches_f64(self):
        from transmogrifai_tpu.ops.embeddings import (
            hash_token_ids, mean_pool_docs)
        rng = np.random.default_rng(0)
        V, dim = 64, 16
        emb = rng.normal(size=(V, dim)).astype(np.float32)
        vocab = [f"tok{i}" for i in range(200)]
        docs = [list(rng.choice(vocab, size=rng.integers(1, 40)))
                for _ in range(100)] + [None, []]
        out = mean_pool_docs(docs, emb)
        assert out.dtype == np.float32
        # f64 reference of the same pooling
        ref = np.zeros((len(docs), dim), np.float64)
        for i, toks in enumerate(docs):
            if not toks:
                continue
            ids = hash_token_ids(list(toks), V)
            ref[i] = emb[ids].astype(np.float64).mean(axis=0)
        assert np.allclose(out, ref, atol=1e-5), \
            np.abs(out - ref).max()
