"""tmoglint: fixture-driven rule tests + baseline freshness + the f32
embeddings tolerance contract (ops/embeddings.py dtype fix, TPU003).

Every rule family has known-bad snippets (must be caught) and known-good
snippets (must stay silent) so rule precision is pinned, not aspirational.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tools.tmoglint.baseline import diff_baseline, load_baseline
from tools.tmoglint.core import LintContext, run_rules, scan_paths

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src: str, path: str = "ops/mod.py", rules=None):
    ctx = LintContext(path, textwrap.dedent(src))
    return run_rules([ctx], only=rules)


def lint_many(named_srcs, rules=None):
    ctxs = [LintContext(p, textwrap.dedent(s)) for p, s in named_srcs]
    return run_rules(ctxs, only=rules)


def rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- TPU001: host sync in hot path ------------------------------------------

class TestTPU001:
    def test_item_in_jitted_fn(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
        """, rules=["TPU001"])
        assert len(out) == 1 and out[0].rule == "TPU001"
        assert ".item()" in out[0].message

    def test_np_asarray_in_scan_body(self):
        out = lint("""
            import jax
            import numpy as np

            def step(c, x):
                return c, np.asarray(x)

            def run(xs):
                return jax.lax.scan(step, 0, xs)
        """, rules=["TPU001"])
        assert rule_lines(out, "TPU001"), "np.asarray in scan body missed"

    def test_float_cast_of_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """, rules=["TPU001"])
        assert len(out) == 1

    def test_block_until_ready_reachable_through_call(self):
        """Hazards in helpers *called from* jitted code are still caught."""
        out = lint("""
            import jax

            def helper(x):
                return x.block_until_ready()

            @jax.jit
            def f(x):
                return helper(x)
        """, rules=["TPU001"])
        assert len(out) == 1

    def test_negative_host_code_untouched(self):
        """The same constructs outside any trace are fine."""
        out = lint("""
            import numpy as np

            def host_fn(x):
                arr = np.asarray(x)
                return float(arr.sum()), arr.tolist()
        """, rules=["TPU001"])
        assert out == []

    def test_negative_scalar_annotated_param(self):
        """float() of a python-scalar-annotated param is static config."""
        out = lint("""
            import jax

            @jax.jit
            def f(x, frac: float = 0.5):
                k = int(round(frac * 8))
                return x * k
        """, rules=["TPU001"])
        assert out == []


# -- TPU002: recompile hazards ----------------------------------------------

class TestTPU002:
    def test_branch_on_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, rules=["TPU002"])
        assert len(out) == 1 and "if" in out[0].message

    def test_static_argnames_typo(self):
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n_binz",))
            def f(x, n_bins):
                return x * n_bins
        """, rules=["TPU002"])
        assert len(out) == 1 and "n_binz" in out[0].message

    def test_fstring_of_traced_param(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                name = f"val={x}"
                return x
        """, rules=["TPU002"])
        assert len(out) == 1

    def test_print_under_trace(self):
        out = lint("""
            import jax

            def body(c, x):
                print("step")
                return c, x

            def run(xs):
                return jax.lax.scan(body, 0, xs)
        """, rules=["TPU002"])
        assert len(out) == 1 and "print" in out[0].message

    def test_array_annotated_static_arg(self):
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("tbl",))
            def f(x, tbl: jax.Array):
                return x
        """, rules=["TPU002"])
        assert len(out) == 1 and "unhashable" in out[0].message

    def test_negative_none_check_and_static_branch(self):
        """`x is None` is static; branches on static args are static;
        branches on shapes are static."""
        out = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("standardize",))
            def f(x, w=None, standardize=True):
                if w is None:
                    w = x * 0 + 1
                if standardize:
                    x = x / 2
                if x.shape[0] > 4:
                    x = x[:4]
                return x * w
        """, rules=["TPU002"])
        assert out == []


# -- TPU003: dtype drift -----------------------------------------------------

class TestTPU003:
    def test_np_float64_in_ops(self):
        out = lint("""
            import numpy as np

            def acc(n):
                return np.zeros((n, n), np.float64)
        """, path="ops/kern.py", rules=["TPU003"])
        assert len(out) == 1 and "float64" in out[0].message

    def test_dtypeless_jnp_zeros_in_ops(self):
        out = lint("""
            import jax.numpy as jnp

            def buf(n):
                return jnp.zeros((n, 8))
        """, path="ops/kern.py", rules=["TPU003"])
        assert len(out) == 1 and "dtype-less" in out[0].message

    def test_negative_outside_kernel_path(self):
        """float64 on a non-ops host path is not TPU003's business."""
        out = lint("""
            import numpy as np

            def acc(n):
                return np.zeros((n, n), np.float64)
        """, path="readers/csv.py", rules=["TPU003"])
        assert out == []

    def test_negative_explicit_dtype_and_asarray(self):
        out = lint("""
            import jax.numpy as jnp

            def buf(x, n):
                a = jnp.zeros((n, 8), jnp.float32)
                b = jnp.asarray(x)  # cast preserves dtype: not a creation
                return a, b
        """, path="ops/kern.py", rules=["TPU003"])
        assert out == []

    def test_suppression_same_line_and_above(self):
        out = lint("""
            import numpy as np

            def acc(n):
                a = np.zeros(n, np.float64)  # tmoglint: disable=TPU003  ABI
                # tmoglint: disable=TPU003  host precision only
                b = np.zeros(n, np.float64)
                return a, b
        """, path="ops/kern.py", rules=["TPU003"])
        assert out == []


# -- TPU004: tracer leak -----------------------------------------------------

class TestTPU004:
    def test_self_assign_in_jitted_method(self):
        out = lint("""
            import jax

            class Model:
                @jax.jit
                def f(self, x):
                    self.cache = x
                    return x
        """, rules=["TPU004"])
        assert len(out) == 1 and "self.cache" in out[0].message

    def test_global_in_scan_body(self):
        out = lint("""
            import jax

            def body(c, x):
                global LAST
                LAST = x
                return c, x

            def run(xs):
                return jax.lax.scan(body, 0, xs)
        """, rules=["TPU004"])
        assert rule_lines(out, "TPU004"), "global stmt under trace missed"

    def test_negative_self_assign_outside_trace(self):
        out = lint("""
            class Model:
                def fit(self, x):
                    self.cache = x
                    return self
        """, rules=["TPU004"])
        assert out == []


# -- TPU005: unsynced wall timing --------------------------------------------

class TestTPU005:
    def test_jnp_call_in_timed_window(self):
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and out[0].rule == "TPU005"
        assert "block_until_ready" in out[0].message

    def test_locally_jitted_name_in_window(self):
        out = lint("""
            import time
            import jax

            f = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.time()
                y = f(x)
                dt = time.time() - t0
                return dt
        """, rules=["TPU005"])
        assert len(out) == 1 and "`f`" in out[0].message

    def test_dispatch_hint_validate(self):
        out = lint("""
            import time

            def sweep(val, X, y):
                t0 = time.perf_counter()
                best = val.validate([(est, grids)], X, y)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and "val.validate" in out[0].message

    def test_negative_block_until_ready_present(self):
        out = lint("""
            import time
            import jax
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                jax.block_until_ready(y)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_negative_host_only_timing(self):
        out = lint("""
            import time
            import numpy as np

            def bench(a, b):
                t0 = time.perf_counter()
                y = np.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_negative_dispatch_outside_window(self):
        """A jax call BEFORE the anchor is not what the delta times."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                y = jnp.dot(a, b)
                t0 = time.perf_counter()
                s = sum(range(100))
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert out == []

    def test_suppression_with_justification(self):
        out = lint("""
            import time

            def sweep(val, X, y):
                t0 = time.perf_counter()
                best = val.validate([(est, grids)], X, y)
                # tmoglint: disable=TPU005  validate returns host floats
                dt = time.perf_counter() - t0
                return dt
        """, rules=["TPU005"])
        assert out == []

    def test_bare_time_import_idiom(self):
        """`from time import time` — bare time() deltas count too."""
        out = lint("""
            from time import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time()
                y = jnp.dot(a, b)
                return time() - t0
        """, rules=["TPU005"])
        assert len(out) == 1

    def test_aliased_jax_numpy_import_is_dispatchish(self):
        """`import jax.numpy as jnumpy` resolves through jnp_aliases
        (like TPU003) — aliasing must not dodge the rule."""
        out = lint("""
            import time
            import jax.numpy as jnumpy

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnumpy.dot(a, b)
                return time.perf_counter() - t0
        """, rules=["TPU005"])
        assert len(out) == 1 and "jnumpy.dot" in out[0].message

    def test_two_anchor_idiom_covers_the_work_between(self):
        """`t0=..; dispatch; t1=..; dt = t1 - t0` — the window spans from
        the EARLIEST anchor in the delta, so the dispatch between the two
        anchors is covered."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                t1 = time.perf_counter()
                dt = t1 - t0
                return dt, y
        """, rules=["TPU005"])
        assert len(out) == 1 and "jnp.dot" in out[0].message

    def test_negative_dispatch_between_two_host_windows(self):
        """A dispatch call BETWEEN two disjoint host-only timed windows is
        untimed: each delta pairs with its own (latest) anchor, windows
        must not merge."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                s1 = sum(range(100))
                d1 = time.perf_counter() - t0
                y = jnp.dot(a, b)
                t0 = time.perf_counter()
                s2 = sum(range(100))
                d2 = time.perf_counter() - t0
                return d1, d2, y
        """, rules=["TPU005"])
        assert out == []

    def test_anchor_reassignment_scopes_each_window(self):
        """Same anchor name reused: only the window whose own span holds
        the dispatch call fires, anchored at THAT delta."""
        out = lint("""
            import time
            import jax.numpy as jnp

            def bench(a, b):
                t0 = time.perf_counter()
                s1 = sum(range(100))
                d1 = time.perf_counter() - t0
                t0 = time.perf_counter()
                y = jnp.dot(a, b)
                d2 = time.perf_counter() - t0
                return d1, d2, y
        """, rules=["TPU005"])
        assert len(out) == 1
        # the finding anchors at d2's line, not d1's
        assert out[0].snippet.startswith("d2")


# -- DAG001: stage contracts -------------------------------------------------

MINI_TYPES = ("pkg/types.py", """
    class FeatureType:
        pass

    class Real(FeatureType):
        pass

    class Text(FeatureType):
        pass
""")


class TestDAG001:
    def test_missing_input_types(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class MyStage(Transformer):
                output_type = Real
        """)], rules=["DAG001"])
        assert len(out) == 1 and "input_types" in out[0].message

    def test_unknown_feature_type_in_contract(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class Widget:
                pass

            class MyStage(Transformer):
                input_types = (Widget,)
                output_type = Real
        """)], rules=["DAG001"])
        assert len(out) == 1 and "Widget" in out[0].message

    def test_set_input_arity_mismatch(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Real)
                output_type = Real
        """), ("pkg/dsl.py", """
            def wire(a):
                return TwoIn().set_input(a).get_output()
        """)], rules=["DAG001"])
        assert len(out) == 1 and "1 input(s)" in out[0].message

    def test_starred_wiring_of_non_sequence_stage(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Real)
                output_type = Real
                is_sequence = False
        """), ("pkg/dsl.py", """
            def wire(feats):
                return TwoIn().set_input(*feats)
        """)], rules=["DAG001"])
        assert len(out) == 1 and "sequence" in out[0].message

    def test_negative_well_formed_stage_and_wiring(self):
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class TwoIn(Transformer):
                input_types = (Real, Text)
                output_type = Real

            class SeqStage(Transformer):
                input_types = (Real,)
                output_type = Real
                is_sequence = True
        """), ("pkg/dsl.py", """
            def wire(a, b, feats):
                x = TwoIn().set_input(a, b).get_output()
                y = SeqStage().set_input(*feats).get_output()
                return x, y
        """)], rules=["DAG001"])
        assert out == []

    def test_negative_dynamic_output_type_binding(self):
        """Passthrough stages that pin output_type per-wiring (in
        set_input) are declared-enough."""
        out = lint_many([MINI_TYPES, ("pkg/stages.py", """
            class Passthrough(Transformer):
                input_types = (Real,)

                def set_input(self, *features):
                    out = super().set_input(*features)
                    self.output_type = features[0].feature_type
                    return out
        """)], rules=["DAG001"])
        assert out == []


# -- real-repo guarantees ----------------------------------------------------

class TestRepoScan:
    @pytest.fixture(scope="class")
    def repo_findings(self):
        ctxs, errors = scan_paths(["transmogrifai_tpu", "tests"], REPO_ROOT)
        return errors + run_rules(ctxs)

    def test_baseline_is_fresh(self, repo_findings):
        """The committed baseline must match a fresh scan exactly: no new
        findings (undeclared debt) and no stale entries (fixed debt whose
        ledger line was never removed)."""
        baseline = load_baseline(
            os.path.join(REPO_ROOT, "tools", "tmoglint", "baseline.json"))
        new, stale = diff_baseline(repo_findings, baseline)
        assert not new, "\n".join(f.render() for f in new)
        assert not stale, f"stale baseline entries: {stale}"

    def test_no_syntax_errors_in_repo(self, repo_findings):
        assert not [f for f in repo_findings if f.rule == "SYNTAX"]


class TestCLI:
    def test_json_report_shape_and_exit_codes(self, tmp_path):
        bad = tmp_path / "ops"
        bad.mkdir()
        (bad / "kern.py").write_text(textwrap.dedent("""
            import numpy as np

            def acc(n):
                return np.zeros(n, np.float64)
        """))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--no-baseline", "--format", "json"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["total_findings"] == 1
        assert report["counts_by_rule"] == {"TPU003": 1}
        assert report["new"][0]["rule"] == "TPU003"
        assert report["ok"] is False
        # writing a baseline makes the same scan green
        base = tmp_path / "base.json"
        proc2 = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--baseline", str(base),
             "--write-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc2.returncode == 0
        proc3 = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "ops",
             "--root", str(tmp_path), "--baseline", str(base)],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc3.returncode == 0, proc3.stdout + proc3.stderr

    def test_write_baseline_with_rule_filter_refused(self, tmp_path):
        """A rule-filtered scan must never overwrite the full baseline."""
        (tmp_path / "clean.py").write_text("x = 1\n")
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--baseline",
             str(tmp_path / "b.json"), "--rules", "TPU003",
             "--write-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 2
        assert "truncate" in proc.stderr
        assert not (tmp_path / "b.json").exists()

    def test_stale_baseline_fails(self, tmp_path):
        """Fixing debt without regenerating the baseline must go red."""
        (tmp_path / "clean.py").write_text("x = 1\n")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"version": 1, "findings": [
            {"fingerprint": "deadbeefdeadbeef", "rule": "TPU003",
             "path": "gone.py", "line": 1, "col": 0,
             "message": "old debt", "snippet": ""}]}))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "clean.py",
             "--root", str(tmp_path), "--baseline", str(base)],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 1
        assert "stale" in proc.stdout


# -- fitted models inherit their estimator's contract ------------------------

class TestFitPinsContract:
    def test_onehot_model_enforces_estimator_types(self):
        """OneHotModel's class contract is (None,) = any, but Estimator.fit
        pins each fitted instance to its estimator's concrete contract."""
        from transmogrifai_tpu.automl.vectorizers.categorical import (
            OneHotVectorizer)
        from transmogrifai_tpu.data.dataset import Dataset
        from transmogrifai_tpu.features.builder import FeatureBuilder
        from transmogrifai_tpu.types import PickList, Real

        rows = [{"cab": c, "age": float(i)}
                for i, c in enumerate(["A", "B", "A", "C"])]
        resp, feats = FeatureBuilder.from_rows(
            rows + [{"cab": "A", "age": 1.0, "y": 0.0}], response="y")
        cab = [f for f in feats if f.name == "cab"][0]
        age = [f for f in feats if f.name == "age"][0]
        assert cab.feature_type is PickList

        est = OneHotVectorizer(top_k=3).set_input(cab)
        ds = Dataset.from_rows(rows, [cab, age]) if \
            hasattr(Dataset, "from_rows") else None
        if ds is None:
            import transmogrifai_tpu.readers.readers as R
            ds = R.ListReader(rows).generate_dataset([cab, age])
        model = est.fit(ds)
        assert model.input_types == est.input_types
        with pytest.raises(TypeError):
            model.set_input(age)  # Real into a Text-pinned fitted pivot

        # the pin must survive a save/load round trip (registry path)
        from transmogrifai_tpu.stages.registry import build_stage
        args = json.loads(json.dumps(model.save_args()))
        rebuilt = build_stage(type(model).__name__, args)
        assert rebuilt.input_types == est.input_types
        with pytest.raises(TypeError):
            rebuilt.set_input(age)


# -- ops/embeddings.py f32 fix (TPU003 satellite) ----------------------------

class TestEmbeddingsF32:
    def test_cooccurrence_counts_exact_in_f32(self):
        from transmogrifai_tpu.ops.embeddings import cooccurrence_matrix
        docs = [["a", "b", "c", "a"], ["b", "c"], None, ["a"]] * 50
        C = cooccurrence_matrix(docs, vocab_bins=16, window=3)
        assert C.dtype == np.float32
        # windowed counts are small integers: f32 must hold them exactly
        assert np.array_equal(C, np.round(C))
        assert np.allclose(C, C.T)

    def test_mean_pool_f32_matches_f64(self):
        from transmogrifai_tpu.ops.embeddings import (
            hash_token_ids, mean_pool_docs)
        rng = np.random.default_rng(0)
        V, dim = 64, 16
        emb = rng.normal(size=(V, dim)).astype(np.float32)
        vocab = [f"tok{i}" for i in range(200)]
        docs = [list(rng.choice(vocab, size=rng.integers(1, 40)))
                for _ in range(100)] + [None, []]
        out = mean_pool_docs(docs, emb)
        assert out.dtype == np.float32
        # f64 reference of the same pooling
        ref = np.zeros((len(docs), dim), np.float64)
        for i, toks in enumerate(docs):
            if not toks:
                continue
            ids = hash_token_ids(list(toks), V)
            ref[i] = emb[ids].astype(np.float64).mean(axis=0)
        assert np.allclose(out, ref, atol=1e-5), \
            np.abs(out - ref).max()
