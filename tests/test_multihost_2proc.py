"""REAL two-process jax.distributed run (CPU backend, localhost
coordinator): the multi-host story executed across process boundaries,
not just the single-process degradation the unit tests cover.

Each child owns 4 virtual devices (global mesh = 8 over 2 processes),
loads only its `process_row_range` slice (the reader-partition analogue),
assembles the global row-sharded array, and runs a jitted Gram reduction
plus a logistic fit whose psums cross the process boundary — the slot
Spark's shuffle and XGBoost's Rabit allreduce occupied in the reference
(SURVEY 2.9). Both children must agree with single-process numpy to f32
tolerance.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = r"""
import json, os
import numpy as np
import jax
from transmogrifai_tpu.parallel import multihost as MH

MH.initialize()
assert jax.process_count() == 2, jax.process_count()
mesh = MH.global_mesh(n_model=1)

n, d = 50, 4  # 50 rows over 8 devices -> padded to 56, tail masked
rng = np.random.default_rng(0)
X_global = rng.normal(size=(n, d)).astype(np.float32)
y_global = (rng.uniform(size=n) < 0.5).astype(np.float32)

start, stop = MH.process_row_range(n)
X = MH.host_local_rows(X_global[start:stop], mesh, n)
y = MH.host_local_rows(y_global[start:stop], mesh, n)
w = MH.host_local_rows(
    np.ones(stop - start, np.float32), mesh, n)  # pad rows -> weight 0

@jax.jit
def gram_and_fit(X, y, w):
    g = (X * w[:, None]).T @ X          # psum over the process boundary
    from transmogrifai_tpu.ops.glm import fit_logistic
    beta, b0 = fit_logistic(X, y, w, 0.1, 0.0)
    return g, beta, b0

with mesh:
    g, beta, b0 = gram_and_fit(X, y, w)
    out = dict(pid=jax.process_index(),
               rows=[int(start), int(stop)],
               gram=np.asarray(g).tolist(),
               beta=np.asarray(beta).tolist(), b0=float(b0))
print("RESULT|" + json.dumps(out), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_and_collect(port):
    """Spawn both children, always reaping/killing BOTH on any failure
    (a dead coordinator otherwise leaves child 1 blocked in distributed
    init for minutes). Returns (outs, error_string_or_None)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            PYTHONPATH=repo,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs, err = [], None
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=120)
            if p.returncode != 0:
                err = err or f"rc={p.returncode}: {stderr[-800:]}"
                continue
            line = next((l for l in stdout.splitlines()
                         if l.startswith("RESULT|")), None)
            if line is None:
                err = err or f"no RESULT line: {stderr[-400:]}"
            else:
                outs.append(json.loads(line[7:]))
    except subprocess.TimeoutExpired:
        err = "distributed child timed out"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs, err


# some jaxlib builds ship a CPU client without cross-process collective
# support at all — the children die in the first psum. That is an
# environment limit, not a repo regression: skip (the single-process
# mesh degradation tests still run everywhere). The message wording
# has drifted across jaxlib releases, so match a family of known
# phrasings rather than one exact string — a new wording must still
# SKIP here, not fail the tier.
_BACKEND_UNSUPPORTED_MARKERS = (
    # <= 0.4.x wording (exact message this test originally pinned)
    "Multiprocess computations aren't implemented on the CPU backend",
    # variants observed across releases / XLA error surfaces
    "not implemented on the CPU backend",
    "not supported on the CPU backend",
    "multi-process computations are not supported",
    "cross-host collectives are not implemented",
    "UNIMPLEMENTED: CollectivePermute",
    "UNIMPLEMENTED: AllReduce",
)


def _backend_unsupported(err: str) -> bool:
    low = err.lower()
    return any(m.lower() in low for m in _BACKEND_UNSUPPORTED_MARKERS)


@pytest.mark.slow
def test_two_process_distributed_matches_numpy():
    # one retry on a fresh port: _free_port closes the socket before the
    # coordinator binds it, so a busy host can steal it in the window
    outs, err = _spawn_and_collect(_free_port())
    if err is not None and not _backend_unsupported(err):
        outs, err = _spawn_and_collect(_free_port())
    if err is not None and _backend_unsupported(err):
        pytest.skip("this jaxlib's CPU backend does not implement "
                    "multiprocess computations (environment limit, "
                    "not a repo regression): " + err[:200])
    assert err is None, err
    assert len(outs) == 2

    # both processes computed the SAME replicated results
    np.testing.assert_allclose(outs[0]["gram"], outs[1]["gram"], rtol=1e-5)
    np.testing.assert_allclose(outs[0]["beta"], outs[1]["beta"], rtol=1e-5)

    # and they match single-process numpy ground truth
    n, d = 50, 4
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    np.testing.assert_allclose(outs[0]["gram"], X.T @ X, rtol=1e-4)

    # row ranges partition the real rows exactly (process 0 first)
    assert outs[0]["rows"][0] == 0
    assert outs[0]["rows"][1] == outs[1]["rows"][0]
    assert outs[1]["rows"][1] == n

    # beta sanity vs an unsharded device fit
    from transmogrifai_tpu.ops.glm import fit_logistic
    import jax.numpy as jnp
    beta1, b01 = fit_logistic(jnp.asarray(X), jnp.asarray(y),
                              jnp.ones(n, jnp.float32), 0.1, 0.0)
    np.testing.assert_allclose(outs[0]["beta"], np.asarray(beta1), atol=2e-3)
