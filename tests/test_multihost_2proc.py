"""REAL two-process jax.distributed runs (CPU backend, localhost
coordinator): the multi-host story executed across process boundaries,
not just the single-process degradation the unit tests cover.

Both tests launch through parallel/launch.launch_local_pod — the same
harness ci.sh's multihost smoke and bench.py --multihost use — so the
children get the full pod environment (gloo collectives flag, virtual
device count, TMOG_* topology knobs) and deadline/containment for free.

`test_two_process_distributed_matches_numpy` keeps the original story: a
hand-rolled Gram + logistic fit whose psums cross the process boundary,
checked against single-process numpy.

`test_two_process_fit_pipeline_parity` is the PR's acceptance run: the
ACTUAL engines (fused + streamed stats, GLM Gram/IRLS sweeps, sharded
fold-fused GBT) on an UNEVEN contiguous row split (12 + 11), each child
holding only its stripe, every merge a cross-host collective. Tree
structure and integer histogram counts must match the single-device
reference EXACTLY; float statistics to documented f32-psum tolerance.
"""
import numpy as np
import pytest

from transmogrifai_tpu.parallel.launch import launch_local_pod

_GRAM_CHILD = r"""
import json, os
import numpy as np
from transmogrifai_tpu.parallel import multihost as MH

MH.initialize()
import jax
assert jax.process_count() == 2, jax.process_count()
mesh = MH.global_mesh(n_model=1)

n, d = 50, 4  # 50 rows over 8 devices -> padded to 56, tail masked
rng = np.random.default_rng(0)
X_global = rng.normal(size=(n, d)).astype(np.float32)
y_global = (rng.uniform(size=n) < 0.5).astype(np.float32)

start, stop = MH.process_row_range(n)
X = MH.host_local_rows(X_global[start:stop], mesh, n)
y = MH.host_local_rows(y_global[start:stop], mesh, n)
w = MH.host_local_rows(
    np.ones(stop - start, np.float32), mesh, n)  # pad rows -> weight 0

@jax.jit
def gram_and_fit(X, y, w):
    g = (X * w[:, None]).T @ X          # psum over the process boundary
    from transmogrifai_tpu.ops.glm import fit_logistic
    beta, b0 = fit_logistic(X, y, w, 0.1, 0.0)
    return g, beta, b0

with mesh:
    g, beta, b0 = gram_and_fit(X, y, w)
    out = dict(pid=jax.process_index(), ospid=os.getpid(),
               rows=[int(start), int(stop)],
               gram=np.asarray(g).tolist(),
               beta=np.asarray(beta).tolist(), b0=float(b0))
print("RESULT|" + json.dumps(out), flush=True)
MH.finalize()
"""

# The whole fit pipeline: each child holds ONLY its contiguous stripe of
# the 23-row dataset (12 + 11 — deliberately uneven so the row_layout
# padding path is exercised), and every engine's merge is a pod psum.
_PIPELINE_CHILD = r"""
import json, os
import numpy as np
from transmogrifai_tpu.parallel import multihost as MH

MH.initialize()
import jax, jax.numpy as jnp
pc = jax.process_count(); pid = jax.process_index()
mesh = MH.global_mesh(n_model=2)

rng = np.random.default_rng(0)
n, d = 23, 3
X = rng.normal(size=(n, d)).astype(np.float32)
# structured label: tree split gains well separated from zero, so the
# psum reduction order cannot flip a gain>0 guard (degenerate gain==0
# nodes are order-sensitive by construction — docs/performance.md)
y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n) > 0
     ).astype(np.float32)
w = (0.5 + rng.random(n)).astype(np.float32)
masks = np.zeros((2, n), np.float32)
masks[0, ::2] = 1.0
masks[1, 1::2] = 1.0
bounds = [0, 12, n] if pc == 2 else [0, n]
lo, hi = bounds[pid], bounds[pid + 1]

def err(a, b):
    a = np.asarray(a, np.float64); b = np.asarray(b, np.float64)
    return float(np.max(np.abs(a - b))) if a.size else 0.0

out = {"pc": pc, "pid": pid, "ospid": os.getpid()}

from transmogrifai_tpu.ops import stats_engine as SE
st, _ = SE.fused_stats_sharded(mesh, X[lo:hi], y[lo:hi], w[lo:hi],
                               corr_matrix=True)
ref, _ = SE.fused_stats(jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
                        corr_matrix=True)
out["stats_mean_err"] = err(st.mean, ref.mean)
out["stats_m2_err"] = err(st.m2, ref.m2)
out["stats_cnt_err"] = err(st.cnt, ref.cnt)

# integer histogram counts (unit weights): EXACT equality required —
# integer sums are reduction-order invariant below 2**24
ones = np.ones(n, np.float32)
lo_v = np.full(d, -3.0, np.float32); hi_v = np.full(d, 3.0, np.float32)
sth, _ = SE.fused_stats_sharded(mesh, X[lo:hi], y[lo:hi], ones[lo:hi],
                                lo=lo_v, hi=hi_v, bins=8)
refh, _ = SE.fused_stats(jnp.asarray(X), jnp.asarray(y),
                         jnp.asarray(ones), lo=jnp.asarray(lo_v),
                         hi=jnp.asarray(hi_v), bins=8)
out["hist_err"] = err(sth.hist, refh.hist)
out["hist_total"] = float(np.sum(np.asarray(sth.hist)))

from transmogrifai_tpu.parallel import tileplane as TP
src = TP.ArraySource(X[lo:hi], y[lo:hi], w[lo:hi], chunk_rows=5)
st2, _ = SE.stream_stats(src, None, None, tile_rows=8, mesh=mesh)
out["stream_mean_err"] = err(st2.mean, ref.mean)
out["stream_cnt_err"] = err(st2.cnt, ref.cnt)

from transmogrifai_tpu.ops import glm_sweep as GS
regs = np.asarray([0.1, 1.0], np.float32)
alphas = np.asarray([0.0, 0.5], np.float32)
B2, b02, _ = GS.sweep_glm_squared_gram_sharded(
    mesh, X[lo:hi], y[lo:hi], w[lo:hi], masks[:, lo:hi], regs, alphas)
B1, b01, _ = GS.sweep_glm_squared_gram(
    jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), jnp.asarray(masks),
    jnp.asarray(regs), jnp.asarray(alphas))
out["glm_gram_err"] = max(err(B2, B1), err(b02, b01))
B4, b04 = GS.sweep_glm_streamed_sharded(
    mesh, X[lo:hi], y[lo:hi], w[lo:hi], masks[:, lo:hi], regs, alphas,
    loss="logistic")
B3, b03 = GS.sweep_glm_streamed(
    jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), jnp.asarray(masks),
    jnp.asarray(regs), jnp.asarray(alphas), loss="logistic")
out["glm_irls_err"] = max(err(B4, B3), err(b04, b03))

from transmogrifai_tpu.ops import trees as T
edges = T.quantile_edges(jnp.asarray(X), 16)
Xb = np.asarray(T.bin_matrix(jnp.asarray(X), edges))
W = masks * w[None, :]
key = jax.random.PRNGKey(0)
trees2, base2, marg2 = T.fit_gbt_folds_sharded(
    Xb[lo:hi], y[lo:hi], W[:, lo:hi], key, mesh=mesh, n_rounds=3,
    depth=2, n_bins=16, learning_rate=0.3, loss="logistic")
trees1, base1, marg1 = T.fit_gbt_folds(
    jnp.asarray(Xb), jnp.asarray(y), jnp.asarray(W), key, n_rounds=3,
    depth=2, n_bins=16, learning_rate=0.3, loss="logistic")
out["tree_feat_exact"] = bool(
    np.array_equal(np.asarray(trees2.feat), np.asarray(trees1.feat)))
out["tree_thresh_exact"] = bool(
    np.array_equal(np.asarray(trees2.thresh), np.asarray(trees1.thresh)))
out["tree_leaf_err"] = err(trees2.leaf, trees1.leaf)
out["tree_margin_err"] = err(marg2, np.asarray(marg1)[:, lo:hi])
out["base_err"] = err(base2, base1)

print("RESULT|" + json.dumps(out), flush=True)
MH.finalize()
"""


# some jaxlib builds ship a CPU client without cross-process collective
# support at all — the children die in the first psum. That is an
# environment limit, not a repo regression: skip (the single-process
# mesh degradation tests still run everywhere). The message wording
# has drifted across jaxlib releases, so match a family of known
# phrasings rather than one exact string — a new wording must still
# SKIP here, not fail the tier.
_BACKEND_UNSUPPORTED_MARKERS = (
    # <= 0.4.x wording (exact message this test originally pinned)
    "Multiprocess computations aren't implemented on the CPU backend",
    # variants observed across releases / XLA error surfaces
    "not implemented on the CPU backend",
    "not supported on the CPU backend",
    "multi-process computations are not supported",
    "cross-host collectives are not implemented",
    "UNIMPLEMENTED: CollectivePermute",
    "UNIMPLEMENTED: AllReduce",
)


def _backend_unsupported(pod) -> bool:
    text = " ".join(c.stderr_tail for c in pod.children).lower()
    return any(m.lower() in text for m in _BACKEND_UNSUPPORTED_MARKERS)


def _run_pod(payload, **kw):
    """launch_local_pod with one retry on a fresh port (free_port closes
    its probe socket before the coordinator binds, so a busy host can
    steal the port in the window) and the backend-unsupported skip."""
    kw.setdefault("n_procs", 2)
    kw.setdefault("devices_per_proc", 4)
    kw.setdefault("timeout", 420.0)
    pod = launch_local_pod(payload, **kw)
    if not pod.ok and not _backend_unsupported(pod):
        pod = launch_local_pod(payload, **kw)
    if not pod.ok and _backend_unsupported(pod):
        pytest.skip("this jaxlib's CPU backend does not implement "
                    "multiprocess computations (environment limit, "
                    "not a repo regression): " + (pod.error or "")[:200])
    assert pod.ok, pod.error
    outs = [pod.result(i) for i in range(kw["n_procs"])]
    assert all(o is not None for o in outs), \
        "child exited 0 without a RESULT| payload"
    # the pod really was two OS processes, each claiming its own rank
    assert len({o["ospid"] for o in outs}) == kw["n_procs"]
    assert sorted(o["pid"] for o in outs) == list(range(kw["n_procs"]))
    return outs


@pytest.mark.slow
def test_two_process_distributed_matches_numpy():
    outs = _run_pod(_GRAM_CHILD, n_procs=2, devices_per_proc=4)

    # both processes computed the SAME replicated results
    np.testing.assert_allclose(outs[0]["gram"], outs[1]["gram"], rtol=1e-5)
    np.testing.assert_allclose(outs[0]["beta"], outs[1]["beta"], rtol=1e-5)

    # and they match single-process numpy ground truth
    n, d = 50, 4
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    np.testing.assert_allclose(outs[0]["gram"], X.T @ X, rtol=1e-4)

    # row ranges partition the real rows exactly (process 0 first)
    outs.sort(key=lambda o: o["pid"])
    assert outs[0]["rows"][0] == 0
    assert outs[0]["rows"][1] == outs[1]["rows"][0]
    assert outs[1]["rows"][1] == n

    # beta sanity vs an unsharded device fit
    from transmogrifai_tpu.ops.glm import fit_logistic
    import jax.numpy as jnp
    beta1, b01 = fit_logistic(jnp.asarray(X), jnp.asarray(y),
                              jnp.ones(n, jnp.float32), 0.1, 0.0)
    np.testing.assert_allclose(outs[0]["beta"], np.asarray(beta1),
                               atol=2e-3)


@pytest.mark.slow
def test_two_process_fit_pipeline_parity():
    """Acceptance run: every fit engine on a real 2-process pod, uneven
    row stripes, vs in-child single-device full-data references."""
    outs = _run_pod(_PIPELINE_CHILD, n_procs=2, devices_per_proc=4)
    for o in outs:
        assert o["pc"] == 2

    # SPMD: both ranks fetched the SAME replicated global results, so
    # every error magnitude must agree bit-for-bit across ranks
    a, b = sorted(outs, key=lambda o: o["pid"])
    for k in ("stats_mean_err", "stats_m2_err", "stats_cnt_err",
              "hist_err", "hist_total", "stream_mean_err",
              "stream_cnt_err", "glm_gram_err", "glm_irls_err",
              "tree_leaf_err", "base_err", "tree_feat_exact",
              "tree_thresh_exact"):
        assert a[k] == b[k], (k, a[k], b[k])

    for o in outs:
        # integer accumulations: exact (reduction-order invariant)
        assert o["hist_err"] == 0.0, o
        assert o["hist_total"] == 23.0 * 3, o  # every (row, col) binned
        assert o["stats_cnt_err"] == 0.0, o
        assert o["stream_cnt_err"] == 0.0, o
        # tree STRUCTURE: exactly the single-device trees
        assert o["tree_feat_exact"], o
        assert o["tree_thresh_exact"], o
        # float sufficient statistics: f32 psum-order tolerance
        assert o["stats_mean_err"] < 1e-6, o
        assert o["stats_m2_err"] < 1e-4, o
        assert o["stream_mean_err"] < 1e-6, o
        assert o["glm_gram_err"] < 1e-4, o
        assert o["glm_irls_err"] < 1e-4, o
        assert o["tree_leaf_err"] < 1e-5, o
        assert o["tree_margin_err"] < 1e-5, o
        assert o["base_err"] < 1e-5, o
