"""Large-sweep machinery: binned rank metrics, bf16 GLM solves, grid-chunked
vmapped sweeps with mid-grid checkpoint resume, mask-fold tree sweeps.

These are the pieces that let the BASELINE.json 10M-row x 64-model x 5-fold
sweep run as a handful of XLA programs inside one HBM budget (reference
workload: core/.../impl/tuning/OpValidator.scala:270-312).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from transmogrifai_tpu.automl.tuning import validators as V
from transmogrifai_tpu.evaluators.evaluators import Evaluators
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.ops import glm as G
from transmogrifai_tpu.ops import metrics_ops as M


def _binary_data(n=3000, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
    p = 1.0 / (1.0 + np.exp(-(X @ beta * 2.0)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


# -- binned rank metrics ----------------------------------------------------

def test_au_pr_binned_matches_exact():
    X, y = _binary_data(5000)
    scores = X[:, 0] * 1.5 + np.random.default_rng(1).normal(size=len(y)) * .5
    w = np.ones_like(y)
    exact = float(M.au_pr(jnp.asarray(scores), jnp.asarray(y), jnp.asarray(w)))
    binned = float(M.au_pr_binned(jnp.asarray(scores), jnp.asarray(y),
                                  jnp.asarray(w), n_bins=4096))
    assert abs(exact - binned) < 2e-3, (exact, binned)


def test_au_roc_binned_matches_exact():
    X, y = _binary_data(5000, seed=3)
    scores = X @ np.ones(X.shape[1], np.float32)
    exact = float(M.au_roc(jnp.asarray(scores), jnp.asarray(y)))
    binned = float(M.au_roc_binned(jnp.asarray(scores), jnp.asarray(y),
                                   n_bins=4096))
    assert abs(exact - binned) < 2e-3, (exact, binned)


def test_binned_metrics_respect_weights():
    X, y = _binary_data(2000, seed=5)
    scores = X[:, 0]
    w = np.zeros_like(y)
    w[:1000] = 1.0  # second half masked out entirely
    full = float(M.au_pr_binned(jnp.asarray(scores[:1000]),
                                jnp.asarray(y[:1000]), n_bins=2048))
    masked = float(M.au_pr_binned(jnp.asarray(scores), jnp.asarray(y),
                                  jnp.asarray(w), n_bins=2048))
    assert abs(full - masked) < 1e-6


# -- bf16 mixed-precision GLM ----------------------------------------------

def test_fit_logistic_bf16_close_to_f32():
    X, y = _binary_data(4000, d=12, seed=7)
    w = np.ones_like(y)
    args = (jnp.asarray(y), jnp.asarray(w), jnp.asarray(0.01),
            jnp.asarray(0.0))
    b32, i32 = G.fit_logistic(jnp.asarray(X, jnp.float32), *args)
    b16, i16 = G.fit_logistic(jnp.asarray(X, jnp.bfloat16), *args)
    assert b16.dtype == jnp.float32  # solver state promoted
    s32 = np.asarray(X @ np.asarray(b32) + float(i32))
    s16 = np.asarray(X @ np.asarray(b16) + float(i16))
    # ranking must be essentially unchanged
    auroc32 = float(M.au_roc(jnp.asarray(s32), jnp.asarray(y)))
    auroc16 = float(M.au_roc(jnp.asarray(s16), jnp.asarray(y)))
    assert abs(auroc32 - auroc16) < 2e-3, (auroc32, auroc16)


def test_fit_softmax_bf16_close_to_f32():
    rng = np.random.default_rng(11)
    n, d, c = 3000, 6, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(d, c)).astype(np.float32)
    y = np.argmax(X @ B + rng.gumbel(size=(n, c)).astype(np.float32), axis=1)
    Y = np.eye(c, dtype=np.float32)[y]
    w = np.ones(n, np.float32)
    args = (jnp.asarray(Y), jnp.asarray(w), jnp.asarray(0.01),
            jnp.asarray(0.0))
    B32, b032 = G.fit_softmax(jnp.asarray(X, jnp.float32), *args, max_iter=30)
    B16, b016 = G.fit_softmax(jnp.asarray(X, jnp.bfloat16), *args, max_iter=30)
    acc32 = (np.argmax(X @ np.asarray(B32) + np.asarray(b032), 1) == y).mean()
    acc16 = (np.argmax(X @ np.asarray(B16) + np.asarray(b016), 1) == y).mean()
    assert abs(acc32 - acc16) < 0.01, (acc32, acc16)


# -- grid-chunked vmapped sweep --------------------------------------------

def _lr_grids():
    return [{"reg_param": r, "elastic_net_param": a}
            for r in (0.001, 0.01, 0.1) for a in (0.0, 0.5)]


def test_chunked_sweep_matches_unchunked():
    X, y = _binary_data(2500)
    models = [(OpLogisticRegression(max_iter=20), _lr_grids())]
    ev = Evaluators.BinaryClassification.au_pr()
    full = V.CrossValidation(ev, num_folds=3, seed=9).validate(
        models, X, y)
    chunked = V.CrossValidation(ev, num_folds=3, seed=9,
                                grid_chunk=2).validate(models, X, y)
    assert chunked.best_grid == full.best_grid
    for a, b in zip(full.validated, chunked.validated):
        assert a.grid == b.grid
        np.testing.assert_allclose(a.fold_metrics, b.fold_metrics,
                                   rtol=1e-5, atol=1e-6)


def test_vmapped_sweep_checkpoint_resume_mid_grid(tmp_path, monkeypatch):
    X, y = _binary_data(1500)
    grids = _lr_grids()
    ev = Evaluators.BinaryClassification.au_pr()
    ck = str(tmp_path / "sweep.jsonl")

    val = V.CrossValidation(ev, num_folds=3, seed=4, grid_chunk=2)
    val.checkpoint_path = ck
    first = val.validate([(OpLogisticRegression(max_iter=20), grids)], X, y)

    # simulate a preemption that lost the last two chunks: drop the tail
    # records, then resume — only the dropped cells may be re-swept
    with open(ck) as f:
        lines = f.readlines()
    assert len(lines) == len(grids)
    with open(ck, "w") as f:
        f.writelines(lines[:2])

    calls = []
    real_sweep = V._sweep

    def counting_sweep(*a, **kw):
        calls.append(np.asarray(a[4]).shape[0])  # regs per call
        return real_sweep(*a, **kw)

    monkeypatch.setattr(V, "_sweep", counting_sweep)
    val2 = V.CrossValidation(ev, num_folds=3, seed=4, grid_chunk=2)
    val2.checkpoint_path = ck
    resumed = val2.validate([(OpLogisticRegression(max_iter=20), grids)], X, y)

    assert sum(calls) == 4  # only the 4 lost cells re-swept (2 chunks of 2)
    assert resumed.best_grid == first.best_grid
    for a, b in zip(first.validated, resumed.validated):
        np.testing.assert_allclose(a.fold_metrics, b.fold_metrics,
                                   rtol=1e-6, atol=1e-7)


def test_fully_checkpointed_sweep_runs_zero_programs(tmp_path, monkeypatch):
    X, y = _binary_data(1200)
    grids = _lr_grids()[:4]
    ev = Evaluators.BinaryClassification.au_pr()
    ck = str(tmp_path / "sweep.jsonl")
    val = V.CrossValidation(ev, num_folds=2, seed=1, grid_chunk=2)
    val.checkpoint_path = ck
    val.validate([(OpLogisticRegression(max_iter=15), grids)], X, y)

    def boom(*a, **kw):
        raise AssertionError("sweep must not run on a complete checkpoint")

    monkeypatch.setattr(V, "_sweep", boom)
    val2 = V.CrossValidation(ev, num_folds=2, seed=1, grid_chunk=2)
    val2.checkpoint_path = ck
    out = val2.validate([(OpLogisticRegression(max_iter=15), grids)], X, y)
    assert len(out.validated) == len(grids)


# -- mask-fold tree sweep ---------------------------------------------------

def test_mask_fold_tree_sweep_agrees_with_sequential():
    X, y = _binary_data(1200, d=6, seed=21)
    grids = [{"step_size": s, "max_iter": 8, "max_depth": 3}
             for s in (0.05, 0.3)]
    models = lambda: [(OpGBTClassifier(), [dict(g) for g in grids])]
    ev = Evaluators.BinaryClassification.au_pr()
    masked = V.CrossValidation(ev, num_folds=3, seed=2).validate(
        models(), X, y)
    seq = V.CrossValidation(ev, num_folds=3, seed=2,
                            mask_fold_trees=False).validate(models(), X, y)
    assert masked.best_grid == seq.best_grid
    for a, b in zip(masked.validated, seq.validated):
        assert a.grid == b.grid
        # same fold assignment; binning differs (full-column vs train-only
        # quantiles), so metrics agree loosely but rank identically
        np.testing.assert_allclose(a.fold_metrics, b.fold_metrics, atol=0.06)


def test_workflow_train_kill_and_resume(tmp_path, monkeypatch):
    """End-to-end failure recovery: a Workflow.train killed mid-sweep
    resumes from the chunk checkpoints and selects the identical winner
    (SURVEY §5 failure-recovery row — the reference leans on Spark task
    retry; here the sweep itself is restartable)."""
    from transmogrifai_tpu.automl.selectors import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.data.dataset import Dataset
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.types import Real, RealNN
    from transmogrifai_tpu.workflow.workflow import Workflow

    X, y = _binary_data(800, d=3, seed=31)
    ds = Dataset.from_features([
        ("f0", Real, X[:, 0].tolist()), ("f1", Real, X[:, 1].tolist()),
        ("f2", Real, X[:, 2].tolist()), ("label", RealNN, y.tolist()),
    ])

    def build(ck_path):
        feats = [FeatureBuilder.Real(n).extract(
            lambda r, _n=n: r.get(_n)).as_predictor()
            for n in ("f0", "f1", "f2")]
        label = FeatureBuilder.RealNN("label").extract(
            lambda r: r.get("label")).as_response()
        from transmogrifai_tpu.automl.vectorizers.combiner import (
            VectorsCombiner,
        )
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        vec = transmogrify(feats)
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, seed=6, model_types=["OpLogisticRegression"])
        sel.validator.checkpoint_path = ck_path
        sel.validator.grid_chunk = 2
        pred = sel.set_input(label, vec).get_output()
        return Workflow().set_input_dataset(ds).set_result_features(pred)

    ck = str(tmp_path / "wf-sweep.jsonl")

    # first attempt dies after the first chunk lands in the checkpoint
    real_sweep = V._sweep
    state = {"calls": 0}

    def dying_sweep(*a, **kw):
        state["calls"] += 1
        if state["calls"] == 2:
            raise RuntimeError("preempted")
        return real_sweep(*a, **kw)

    monkeypatch.setattr(V, "_sweep", dying_sweep)
    with pytest.raises(RuntimeError, match="preempted"):
        build(ck).train()
    assert len(open(ck).read().splitlines()) >= 1  # partial progress persisted

    monkeypatch.setattr(V, "_sweep", real_sweep)
    model = build(ck).train()  # resumes, finishes

    # uninterrupted reference run (fresh checkpoint): identical winner
    import re

    def winner(m):
        line = m.summary_pretty().split("Selected:")[1].splitlines()[0]
        return re.sub(r"uid \S+", "uid <...>", line)  # uids are run-global

    model_ref = build(str(tmp_path / "fresh.jsonl")).train()
    assert winner(model) == winner(model_ref)


def test_mesh_sharded_sweep_matches_single_device():
    """The same sweep under a (batch, model) device mesh — rows sharded,
    GSPMD-inserted psums — must reproduce the single-device metrics.
    n is chosen NOT divisible by the batch axis to exercise zero-weight
    row padding."""
    from transmogrifai_tpu.parallel.mesh import make_mesh
    X, y = _binary_data(1111, d=6, seed=61)  # 1111 % 4 != 0
    models = lambda: [(OpLogisticRegression(max_iter=20), _lr_grids()[:4])]
    ev = Evaluators.BinaryClassification.au_pr()
    plain = V.CrossValidation(ev, num_folds=3, seed=9).validate(
        models(), X, y)
    mesh = make_mesh(n_batch=4, n_model=2)
    sharded = V.CrossValidation(ev, num_folds=3, seed=9,
                                mesh=mesh).validate(models(), X, y)
    assert sharded.best_grid == plain.best_grid
    for a, b in zip(plain.validated, sharded.validated):
        np.testing.assert_allclose(a.fold_metrics, b.fold_metrics,
                                   rtol=2e-4, atol=2e-5)


def test_mesh_sharded_tree_sweep_matches_single_device():
    from transmogrifai_tpu.parallel.mesh import make_mesh
    X, y = _binary_data(1001, d=5, seed=63)
    grids = [{"step_size": s, "max_iter": 6, "max_depth": 3}
             for s in (0.1, 0.3)]
    models = lambda: [(OpGBTClassifier(), [dict(g) for g in grids])]
    ev = Evaluators.BinaryClassification.au_pr()
    plain = V.CrossValidation(ev, num_folds=2, seed=3).validate(
        models(), X, y)
    mesh = make_mesh(n_batch=8, n_model=1)
    sharded = V.CrossValidation(ev, num_folds=2, seed=3,
                                mesh=mesh).validate(models(), X, y)
    assert sharded.best_grid == plain.best_grid
    # padding repeats a real row inside the unweighted quantile sample, so
    # bin edges (and an occasional split) may shift marginally
    for a, b in zip(plain.validated, sharded.validated):
        np.testing.assert_allclose(a.fold_metrics, b.fold_metrics,
                                   atol=2e-2)


def test_checkpoint_does_not_cross_sweep_paths(tmp_path):
    """Metrics from the mask-fold path must NOT be replayed into a
    physically-split rerun (they can differ enough to flip the winner) —
    the checkpoint key carries the compute path."""
    X, y = _binary_data(700, d=4, seed=47)
    grids = [{"step_size": 0.2, "max_iter": 5, "max_depth": 3}]
    ev = Evaluators.BinaryClassification.au_pr()
    ck = str(tmp_path / "sweep.jsonl")
    v1 = V.CrossValidation(ev, num_folds=2, seed=5)
    v1.checkpoint_path = ck
    v1.validate([(OpGBTClassifier(), [dict(g) for g in grids])], X, y)
    n_records = len(open(ck).read().splitlines())

    v2 = V.CrossValidation(ev, num_folds=2, seed=5, mask_fold_trees=False)
    v2.checkpoint_path = ck
    v2.validate([(OpGBTClassifier(), [dict(g) for g in grids])], X, y)
    assert len(open(ck).read().splitlines()) == 2 * n_records, \
        "sequential rerun must compute its own cells, not reuse mask-fold's"


def test_mask_fold_sweep_honors_max_bins_grid():
    """max_bins is itself a grid axis: the binned context must be rebuilt
    per distinct value, not frozen from the base estimator."""
    X, y = _binary_data(800, d=4, seed=41)
    grids = [{"max_bins": 4, "max_iter": 5, "max_depth": 3},
             {"max_bins": 64, "max_iter": 5, "max_depth": 3}]
    ev = Evaluators.BinaryClassification.au_pr()
    out = V.CrossValidation(ev, num_folds=2, seed=2).validate(
        [(OpGBTClassifier(), grids)], X, y)
    a, b = out.validated
    assert a.fold_metrics != b.fold_metrics, \
        "4-bin and 64-bin cells returned identical metrics — ctx not rebuilt"


def test_mask_fold_multiclass_sweep_with_two_classes():
    """problem_type='multiclass' over 2-class labels must still produce
    [F, n, c] scores (the metric fn argmaxes over axis 1)."""
    from transmogrifai_tpu.models.trees import OpXGBoostClassifier
    X, y = _binary_data(600, d=4, seed=43)
    grids = [{"num_round": 4, "max_depth": 3, "max_bins": 16}]
    ev = Evaluators.MultiClassification.f1()
    out = V.CrossValidation(ev, num_folds=2, seed=2).validate(
        [(OpXGBoostClassifier(), grids)], X, y, problem_type="multiclass")
    assert all(np.isfinite(v) for v in out.validated[0].fold_metrics)


def test_mask_fold_tree_sweep_checkpoints(tmp_path, monkeypatch):
    X, y = _binary_data(900, d=5, seed=23)
    grids = [{"step_size": s, "max_iter": 6, "max_depth": 3}
             for s in (0.1, 0.3)]
    ev = Evaluators.BinaryClassification.au_pr()
    ck = str(tmp_path / "trees.jsonl")
    val = V.CrossValidation(ev, num_folds=2, seed=3)
    val.checkpoint_path = ck
    first = val.validate([(OpGBTClassifier(), [dict(g) for g in grids])],
                         X, y)
    # resume must not refit anything
    import transmogrifai_tpu.models.trees as MT

    def boom(*a, **kw):
        raise AssertionError("mask_fit_scores must not run on resume")

    monkeypatch.setattr(MT._TreeEstimator, "mask_fit_scores", boom)
    val2 = V.CrossValidation(ev, num_folds=2, seed=3)
    val2.checkpoint_path = ck
    resumed = val2.validate([(OpGBTClassifier(), [dict(g) for g in grids])],
                            X, y)
    assert resumed.best_grid == first.best_grid


def _grid_fuse_sweep(X, y, grids, monkeypatch, max_failures):
    """Drive one mask-fold tree sweep with the config-fused route opt-in
    and mask_fit_scores_grid monkeypatched to raise."""
    import transmogrifai_tpu.models.trees as MT

    monkeypatch.setenv("TMOG_GRID_FUSE", "1")
    monkeypatch.setenv("TMOG_GRID_FUSE_MAX_FAILURES", str(max_failures))

    def boom(*a, **kw):
        raise ValueError("injected fused-kernel failure")

    monkeypatch.setattr(MT._TreeEstimator, "mask_fit_scores_grid", boom)
    ev = Evaluators.BinaryClassification.au_pr()
    return V.CrossValidation(ev, num_folds=2, seed=2).validate(
        [(OpGBTClassifier(), [dict(g) for g in grids])], X, y)


def test_grid_fuse_failure_falls_back_with_one_warning(monkeypatch, caplog):
    """A fused-route failure below the cap falls back per-config and
    surfaces ONE sweep-level warning (not a per-config stream) — the
    warning call itself must execute (it once NameError'd on an
    undefined cap variable, killing the sweep the fallback was meant to
    save)."""
    import logging
    X, y = _binary_data(600, d=4, seed=31)
    grids = [{"step_size": s, "max_iter": 4, "max_depth": 2}
             for s in (0.05, 0.3)]  # same fuse signature -> one group
    with caplog.at_level(logging.WARNING,
                         logger="transmogrifai_tpu.automl.tuning.validators"):
        out = _grid_fuse_sweep(X, y, grids, monkeypatch, max_failures=3)
    assert all(np.isfinite(v) for m in out.validated
               for v in m.fold_metrics)
    # per-config fallback, so no cell is attributed to the fused program
    assert all(m.route == "mask_folds" for m in out.validated)
    warn = [r for r in caplog.records if "falling back" in r.message]
    assert len(warn) == 1, "exactly one sweep-level fallback warning"


def test_grid_fuse_repeated_failures_raise_at_cap(monkeypatch):
    """At TMOG_GRID_FUSE_MAX_FAILURES consecutive fused-route failures
    the sweep raises instead of silently degrading per-config forever
    (ADVICE r5)."""
    X, y = _binary_data(600, d=4, seed=31)
    grids = [{"step_size": s, "max_iter": 4, "max_depth": 2}
             for s in (0.05, 0.3)]
    with pytest.raises(RuntimeError, match="fused sweep route failed"):
        _grid_fuse_sweep(X, y, grids, monkeypatch, max_failures=1)
