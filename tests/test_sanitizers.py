"""Sanitizer subsystem: NaN/Inf trapping, finiteness audit, purity laws.

SURVEY §5 race-detection/sanitizers row — the compiled-pipeline analogues
of the reference's closure-serializability checks (OpWorkflow.scala:265).
"""
from __future__ import annotations

import numpy as np
import pytest

from transmogrifai_tpu.data.dataset import Dataset, column_from_values
from transmogrifai_tpu.testkit.feature_builder import TestFeatureBuilder
from transmogrifai_tpu.types import Real, RealNN
from transmogrifai_tpu.utils.sanitizers import (
    assert_stage_pure, check_finite, debug_nans,
)


def test_debug_nans_traps_and_restores():
    import jax
    import jax.numpy as jnp
    prev = jax.config.jax_debug_nans
    with debug_nans():
        with pytest.raises(FloatingPointError):
            jnp.asarray(0.0) / jnp.asarray(0.0)
    assert jax.config.jax_debug_nans == prev
    # NaN passes silently outside the scope
    assert np.isnan(float(jnp.asarray(0.0) / jnp.asarray(0.0)))


def test_check_finite_flags_vector_defects_not_missing():
    from transmogrifai_tpu.data.dataset import Column
    from transmogrifai_tpu.types import ColumnKind
    vec = np.array([[1.0, np.nan], [np.inf, 2.0]], np.float32)
    ds = Dataset({
        "num": column_from_values(Real, [1.0, None, 3.0]),
    })
    ds2 = Dataset({
        "num": column_from_values(Real, [1.0, None]),
        "vec": Column(kind=ColumnKind.VECTOR, data=vec),
    })
    assert check_finite(ds) == {}  # NaN in a Real column = missing, fine
    rep = check_finite(ds2)
    assert rep == {"vec": {"nan": 1, "inf": 1}}


def test_assert_stage_pure_passes_for_real_stage():
    from transmogrifai_tpu.automl.preparators import SanityChecker
    rng = np.random.default_rng(0)
    X = rng.normal(size=(120, 3))
    y = (X[:, 0] > 0).astype(float)
    ds, (label, *fs) = TestFeatureBuilder.build(
        ("label", RealNN, y.tolist()),
        *[(f"f{i}", Real, X[:, i].tolist()) for i in range(3)],
        response_index=0)
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    vec = transmogrify(list(fs))
    stage = vec.origin_stage
    # walk the tiny dag: fit each layer onto the dataset
    from transmogrifai_tpu.workflow.workflow import Workflow
    model = Workflow().set_input_dataset(ds).set_result_features(vec).train()
    out = model.score(ds)
    checker = SanityChecker(check_sample=1.0).set_input(label, vec)
    assert_stage_pure(checker, out.with_column(
        "label", ds.column("label")))


def test_assert_stage_pure_catches_mutation():
    from transmogrifai_tpu.stages.base import Transformer
    from transmogrifai_tpu.data.dataset import Column
    from transmogrifai_tpu.types import ColumnKind

    class Mutator(Transformer):
        input_types = (Real,)
        output_type = Real

        def __init__(self, **kw):
            super().__init__("mutator", **kw)

        def transform_columns(self, *cols):
            cols[0].data[0] = 999.0  # mutates shared input
            return Column(kind=ColumnKind.FLOAT, data=cols[0].data.copy())

    ds, (f,) = TestFeatureBuilder.build(("x", Real, [1.0, 2.0]))
    with pytest.raises(AssertionError, match="mutated"):
        assert_stage_pure(Mutator().set_input(f), ds)


def test_runner_debug_nans_flag():
    """OpParams.debug_nans wraps the whole run in the NaN trap and
    round-trips through JSON (reference OpParams flag style)."""
    import jax
    from transmogrifai_tpu.workflow.runner import OpParams
    p = OpParams(debug_nans=True)
    assert OpParams.from_json(p.to_json()).debug_nans is True
    prev = jax.config.jax_debug_nans
    from transmogrifai_tpu.utils.sanitizers import debug_nans
    with debug_nans():
        assert jax.config.jax_debug_nans is True
    assert jax.config.jax_debug_nans == prev
