"""Multi-host plumbing (parallel/multihost.py) on the single-process
virtual 8-device mesh: row-range partitioning, global-array assembly, and
that a sharded reduction over the assembled array matches numpy (the DCN
collective slot — single-process exercises the same code path)."""
import numpy as np

import jax
import jax.numpy as jnp

from transmogrifai_tpu.parallel import multihost as MH
from transmogrifai_tpu.parallel.mesh import BATCH_AXIS


def test_initialize_single_process_noop():
    MH.initialize()          # no coordinator: must be a safe no-op
    MH.initialize()          # idempotent
    assert MH.process_count() == 1


def test_process_row_range_covers_exactly():
    # single process: the whole range
    assert MH.process_row_range(10) == (0, 10)


def test_global_mesh_axes():
    mesh = MH.global_mesh(n_model=2)
    assert set(mesh.axis_names) == {"batch", "model"}
    assert mesh.devices.size == len(jax.devices())


def test_host_local_rows_roundtrip_and_reduction():
    mesh = MH.global_mesh(n_model=1)
    n, d = 64, 5
    rng = np.random.default_rng(0)
    local = rng.normal(size=(n, d)).astype(np.float32)
    start, stop = MH.process_row_range(n)
    arr = MH.host_local_rows(local[start:stop], mesh, n)
    assert arr.shape == (n, d)
    np.testing.assert_allclose(np.asarray(arr), local, rtol=1e-6)
    # a Gram reduction over the row-sharded array == numpy (the psum/DCN
    # slot: XLA inserts the cross-device reduction)
    gram = jax.jit(lambda x: x.T @ x)(arr)
    np.testing.assert_allclose(np.asarray(gram), local.T @ local, atol=1e-3)


def test_host_local_rows_1d():
    mesh = MH.global_mesh()
    y = np.arange(32, dtype=np.float32)
    arr = MH.host_local_rows(y, mesh, 32)
    np.testing.assert_allclose(np.asarray(arr), y)


def test_non_divisible_rows_pad_to_device_multiple():
    """Row counts that don't divide the device count pad at the tail,
    masked by mesh.row_mask (the review-found crash case)."""
    from transmogrifai_tpu.parallel.mesh import row_mask
    mesh = MH.global_mesh()
    n, d = 10, 3   # 8 devices: pads to 16
    rng = np.random.default_rng(1)
    local = rng.normal(size=(n, d)).astype(np.float32)
    s_, e_ = MH.process_row_range(n)
    arr = MH.host_local_rows(local[s_:e_], mesh, n)
    padded = MH.padded_global_rows(n)
    assert arr.shape == (padded, d)
    np.testing.assert_allclose(np.asarray(arr)[:n], local, rtol=1e-6)
    mask = row_mask(padded, n)
    assert mask.sum() == n
    # weighted sum over real rows only == numpy
    w = jnp.asarray(mask, jnp.float32)
    tot = jax.jit(lambda x, w: (x * w[:, None]).sum(0))(arr, w)
    np.testing.assert_allclose(np.asarray(tot), local.sum(0), atol=1e-4)


def test_fetch_global_single_process_matches_asarray():
    """fetch_global — the documented cross-process fold SHD005 points at
    — degrades to a plain asarray at one process; the host reduce over
    it is then the true global reduce."""
    mesh = MH.global_mesh(n_model=1)
    n, d = 32, 4
    rng = np.random.default_rng(2)
    local = rng.normal(size=(n, d)).astype(np.float32)
    s_, e_ = MH.process_row_range(n)
    arr = MH.host_local_rows(local[s_:e_], mesh, n)
    fetched = MH.fetch_global(arr)
    np.testing.assert_allclose(fetched, local, rtol=1e-6)
    np.testing.assert_allclose(np.sum(fetched, axis=0), local.sum(0),
                               rtol=1e-5)


def test_initialize_explicit_coordinator_requires_count(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    import pytest
    with pytest.raises(ValueError):
        MH.initialize(coordinator_address="host:1234")
