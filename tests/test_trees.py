"""Tree-family kernels + estimators.

Mirrors the reference suites OpRandomForest*/OpGBT*/OpDecisionTree*/
OpXGBoost*Test.scala (core/src/test/.../impl/{classification,regression}/):
fitted model emits Prediction(pred, rawPrediction, probability); quality
checks on separable/nonlinear synthetic data; save/load round-trip.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from transmogrifai_tpu.ops import trees as T


def _xor_data(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 4)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
    return X, y


def _blob_data(n=1500, k=3, seed=1):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(k, 5))
    y = rng.integers(0, k, size=n)
    X = centers[y] + rng.normal(size=(n, 5))
    return X.astype(np.float32), y.astype(np.float32)


def _piecewise(n=3000, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.uniform(0, 1, size=(n, 3)).astype(np.float32)
    y = (np.where(X[:, 0] < 0.3, 1.0, 0.0) + 2.0 * (X[:, 1] > 0.6)
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    return X, y


class TestBinning:
    def test_quantile_edges_monotone(self):
        X = np.random.default_rng(0).normal(size=(500, 3)).astype(np.float32)
        edges = np.asarray(T.quantile_edges(jnp.asarray(X), 16))
        assert edges.shape == (3, 15)
        assert (np.diff(edges, axis=1) >= 0).all()

    def test_bin_matrix_range_and_threshold_semantics(self):
        X = np.random.default_rng(1).normal(size=(400, 2)).astype(np.float32)
        edges = T.quantile_edges(jnp.asarray(X), 8)
        Xb = np.asarray(T.bin_matrix(jnp.asarray(X), edges))
        # present values occupy [1, n_bins]; bin 0 is reserved for missing
        assert Xb.min() >= 1 and Xb.max() <= 8
        # bin > t  <=>  x >= edges[t-1] (equality on an edge goes right)
        e = np.asarray(edges)
        t = 3
        assert ((Xb[:, 0] > t) == (X[:, 0] >= e[0, t - 1])).all()

    def test_bin_matrix_missing_bin(self):
        X = np.random.default_rng(2).normal(size=(300, 2)).astype(np.float32)
        X[::7, 0] = np.nan
        edges = T.quantile_edges(jnp.asarray(X), 8)
        Xb = np.asarray(T.bin_matrix(jnp.asarray(X), edges))
        nan = np.isnan(X[:, 0])
        assert (Xb[nan, 0] == 0).all()
        assert (Xb[~nan, 0] >= 1).all()
        # NaN rows are excluded from the quantile sketch: edges of the
        # NaN-carrying column are finite
        assert np.isfinite(np.asarray(edges)[0]).all()

    def test_constant_feature_is_harmless(self):
        X = np.ones((100, 2), np.float32)
        X[:, 1] = np.arange(100)
        edges = T.quantile_edges(jnp.asarray(X), 8)
        Xb = np.asarray(T.bin_matrix(jnp.asarray(X), edges))
        assert (Xb[:, 0] == Xb[0, 0]).all()


class TestGrowTree:
    def test_single_split_recovers_step(self):
        # y = 1[x0 > 0.5]: a depth-1 tree must find feature 0, cut ~0.5
        rng = np.random.default_rng(3)
        X = rng.uniform(0, 1, size=(1000, 3)).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.float32)
        edges = T.quantile_edges(jnp.asarray(X), 32)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        tree = T.grow_tree(Xb, jnp.asarray(y[:, None]),
                           jnp.ones(1000, jnp.float32),
                           jnp.zeros(2, dtype=jnp.uint32),
                           depth=1, n_bins=32, leaf_mode="mean")
        assert int(tree.feat[0]) == 0
        tv = float(np.asarray(T.thresholds_to_values(
            tree.feat, tree.thresh, edges))[0])
        assert 0.4 < tv < 0.6
        leaves = np.asarray(tree.leaf)[:, 0]
        assert leaves[0] < 0.05 and leaves[1] > 0.95

    def test_no_split_when_pure(self):
        X = np.random.default_rng(4).normal(size=(200, 2)).astype(np.float32)
        y = np.ones(200, np.float32)  # pure node: zero gain everywhere
        edges = T.quantile_edges(jnp.asarray(X), 8)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        tree = T.grow_tree(Xb, jnp.asarray(y[:, None]),
                           jnp.ones(200, jnp.float32),
                           jnp.zeros(2, dtype=jnp.uint32),
                           depth=2, n_bins=8, leaf_mode="mean",
                           min_info_gain=1e-6)
        # dead splits encode thresh = n_bins (all rows left; bin 0 is the
        # missing slot so live bins are [1, n_bins])
        assert (np.asarray(tree.thresh) == 8).all()
        # every populated leaf predicts the pure value
        assert np.allclose(np.asarray(tree.leaf)[0, 0], 1.0, atol=1e-5)

    def test_min_instances_respected(self):
        rng = np.random.default_rng(5)
        X = rng.uniform(0, 1, size=(100, 1)).astype(np.float32)
        y = (X[:, 0] > 0.97).astype(np.float32)  # only ~3 positives
        edges = T.quantile_edges(jnp.asarray(X), 64)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        tree = T.grow_tree(Xb, jnp.asarray(y[:, None]),
                           jnp.ones(100, jnp.float32),
                           jnp.zeros(2, dtype=jnp.uint32),
                           depth=1, n_bins=64, leaf_mode="mean",
                           min_instances=10.0)
        n_right = int((np.asarray(Xb)[:, 0] > int(tree.thresh[0])).sum())
        assert n_right >= 10 or int(tree.thresh[0]) == 64


class TestEstimators:
    def test_gbt_classifier_solves_xor(self):
        from transmogrifai_tpu.models.trees import OpGBTClassifier
        X, y = _xor_data()
        m = OpGBTClassifier(max_iter=30, max_depth=3, step_size=0.3)
        model = m.fit_arrays(X, y)
        pred, raw, prob = model.predict_arrays(X)
        assert raw.shape == (len(y), 2) and prob.shape == (len(y), 2)
        assert (pred == y).mean() > 0.95
        assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)

    def test_xgb_classifier_binary_quality(self):
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier
        X, y = _xor_data(seed=7)
        m = OpXGBoostClassifier(num_round=40, max_depth=3, eta=0.3,
                                max_bins=64)
        model = m.fit_arrays(X, y)
        pred, _, prob = model.predict_arrays(X)
        assert (pred == y).mean() > 0.95

    def test_xgb_multiclass_softprob(self):
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier
        X, y = _blob_data()
        m = OpXGBoostClassifier(num_round=15, max_depth=3, eta=0.5,
                                max_bins=32)
        model = m.fit_arrays(X, y)
        pred, raw, prob = model.predict_arrays(X)
        assert prob.shape == (len(y), 3)
        assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)
        assert (pred == y).mean() > 0.9

    def test_random_forest_multiclass(self):
        from transmogrifai_tpu.models.trees import OpRandomForestClassifier
        X, y = _blob_data(seed=11)
        m = OpRandomForestClassifier(num_trees=20, max_depth=5)
        model = m.fit_arrays(X, y)
        pred, _, prob = model.predict_arrays(X)
        assert prob.shape[1] == 3
        assert (pred == y).mean() > 0.9

    def test_decision_tree_classifier(self):
        # axis-aligned AND target (greedy trees cannot break symmetric XOR;
        # boosting/bagging handle that — see the GBT/XGB tests above)
        from transmogrifai_tpu.models.trees import OpDecisionTreeClassifier
        rng = np.random.default_rng(13)
        X = rng.uniform(-1, 1, size=(2000, 4)).astype(np.float32)
        y = ((X[:, 0] > 0) & (X[:, 1] > 0)).astype(np.float32)
        m = OpDecisionTreeClassifier(max_depth=4)
        model = m.fit_arrays(X, y)
        pred, _, _ = model.predict_arrays(X)
        assert (pred == y).mean() > 0.95
        assert model.feat.shape[0] == 1  # single tree

    def test_gbt_regressor_piecewise(self):
        from transmogrifai_tpu.models.trees import OpGBTRegressor
        X, y = _piecewise()
        m = OpGBTRegressor(max_iter=40, max_depth=3, step_size=0.3,
                           max_bins=128)
        model = m.fit_arrays(X, y)
        pred, raw, prob = model.predict_arrays(X)
        assert raw is None and prob is None
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        assert rmse < 0.2

    def test_random_forest_regressor(self):
        from transmogrifai_tpu.models.trees import OpRandomForestRegressor
        X, y = _piecewise(seed=17)
        m = OpRandomForestRegressor(num_trees=30, max_depth=6)
        model = m.fit_arrays(X, y)
        pred, _, _ = model.predict_arrays(X)
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        assert rmse < 0.3

    def test_xgb_regressor(self):
        from transmogrifai_tpu.models.trees import OpXGBoostRegressor
        X, y = _piecewise(seed=19)
        m = OpXGBoostRegressor(num_round=50, max_depth=3, eta=0.3,
                               max_bins=64)
        model = m.fit_arrays(X, y)
        pred, _, _ = model.predict_arrays(X)
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        assert rmse < 0.2

    def test_decision_tree_regressor(self):
        from transmogrifai_tpu.models.trees import OpDecisionTreeRegressor
        X, y = _piecewise(seed=23)
        m = OpDecisionTreeRegressor(max_depth=4)
        model = m.fit_arrays(X, y)
        pred, _, _ = model.predict_arrays(X)
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        assert rmse < 0.35

    def test_sample_weights_shift_model(self):
        from transmogrifai_tpu.models.trees import OpGBTClassifier
        X, y = _xor_data(seed=29)
        w_pos = np.where(y > 0, 10.0, 0.1).astype(np.float32)
        m = OpGBTClassifier(max_iter=10, max_depth=3)
        p_w = m.fit_arrays(X, y, w_pos).predict_arrays(X)[2][:, 1].mean()
        p_u = m.fit_arrays(X, y).predict_arrays(X)[2][:, 1].mean()
        assert p_w > p_u + 0.1  # upweighting positives raises P(y=1)


class TestServingParity:
    def test_binned_and_raw_traversal_agree_on_onehot(self):
        # regression: one-hot values sit exactly on their bin edge; serving
        # must use x >= thresh to match `bin > t` (right-side binning)
        import jax
        rng = np.random.default_rng(43)
        X = np.concatenate([
            rng.uniform(0, 1, size=(800, 2)),
            (rng.uniform(size=(800, 2)) < 0.4).astype(np.float64),
        ], axis=1).astype(np.float32)
        y = ((X[:, 2] > 0.5) | (X[:, 0] > 0.7)).astype(np.float32)
        edges = T.quantile_edges(jnp.asarray(X), 32)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        trees, base = T.fit_gbt(Xb, jnp.asarray(y),
                                jnp.ones(800, jnp.float32),
                                jax.random.PRNGKey(0), n_rounds=5, depth=3,
                                n_bins=32, learning_rate=0.3,
                                loss="logistic")
        binned = float(base) + np.asarray(
            T.predict_forest_bins(trees, Xb, 3))[:, 0]
        tv = np.asarray(T.thresholds_to_values(trees.feat, trees.thresh,
                                               edges))
        raw = float(base) + T.np_predict_ensemble(
            np.asarray(trees.feat), tv, np.asarray(trees.leaf), X, 3)[:, 0]
        assert np.allclose(binned, raw, atol=1e-5)

    def test_nan_features_agree_between_binned_and_raw(self):
        # NaN occupies the dedicated bin 0 and routes by each node's
        # LEARNED default direction (Tree.miss); raw serving applies the
        # same bit on isnan rows — train and serve must agree when a NaN
        # escapes imputation
        import jax
        rng = np.random.default_rng(7)
        X = rng.normal(size=(600, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        X[rng.uniform(size=600) < 0.15, 0] = np.nan
        X[rng.uniform(size=600) < 0.1, 2] = np.nan
        # quantile_edges sees the raw NaN matrix, same as models/trees._bin
        edges = T.quantile_edges(jnp.asarray(X), 16)
        assert np.isfinite(np.asarray(edges)[:, -1]).all()  # not NaN-poisoned
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        trees, base = T.fit_gbt(Xb, jnp.asarray(y),
                                jnp.ones(600, jnp.float32),
                                jax.random.PRNGKey(1), n_rounds=4, depth=3,
                                n_bins=16, learning_rate=0.3,
                                loss="logistic")
        binned = float(base) + np.asarray(
            T.predict_forest_bins(trees, Xb, 3))[:, 0]
        tv = np.asarray(T.thresholds_to_values(trees.feat, trees.thresh,
                                               edges))
        raw = float(base) + T.np_predict_ensemble(
            np.asarray(trees.feat), tv, np.asarray(trees.leaf), X, 3,
            miss=np.asarray(trees.miss))[:, 0]
        assert np.isfinite(binned).all()
        assert np.allclose(binned, raw, atol=1e-5)
        # the missing mass is informative here (y depends on x0 which is
        # NaN-ed at random): some node learns default-right across rounds
        # (5/28 at this seed), proving the direction is actually used —
        # if learning regressed to always-left this catches it
        assert (np.asarray(trees.miss) > 0).any()


class TestPersistence:
    def test_tree_model_save_load_round_trip(self, tmp_path):
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier
        from transmogrifai_tpu.stages.registry import (
            pack_args, unpack_args, build_stage)
        X, y = _xor_data(seed=31)
        model = OpXGBoostClassifier(num_round=5, max_depth=3).fit_arrays(X, y)
        store = {}
        packed = pack_args(model.save_args(), store, model.uid)
        restored = build_stage(type(model).__name__,
                               unpack_args(packed, store))
        p1 = model.predict_arrays(X)[2]
        p2 = restored.predict_arrays(X)[2]
        assert np.allclose(p1, p2, atol=1e-6)

    def test_softmax_model_round_trip(self):
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier
        X, y = _blob_data(seed=37)
        model = OpXGBoostClassifier(num_round=3, max_depth=2).fit_arrays(X, y)
        args = model.save_args()
        cls = type(model)
        restored = cls.from_save_args(args)
        assert np.allclose(model.predict_arrays(X)[2],
                           restored.predict_arrays(X)[2], atol=1e-6)


class TestSelectorIntegration:
    def test_binary_selector_with_trees(self):
        from transmogrifai_tpu.automl.selectors import (
            BinaryClassificationModelSelector)
        from transmogrifai_tpu.models.trees import OpGBTClassifier
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        from transmogrifai_tpu.stages.params import param_grid
        X, y = _xor_data(n=600, seed=41)
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            models_and_parameters=[
                (OpLogisticRegression(), param_grid(reg_param=[0.01])),
                (OpGBTClassifier(), param_grid(max_iter=[10], max_depth=[3])),
            ])
        best = sel.fit_arrays(X, y)
        # XOR is not linearly separable: trees must win the sweep
        assert best.summary.best_model_type == "OpGBTClassifier"


class TestHistogramPaths:
    """The TPU matmul-histogram path must agree with the segment-sum path
    (they are alternative lowerings of the same reduction; grow_tree picks
    by backend, so CPU tests exercise the matmul path explicitly here)."""

    def _inputs(self, n=1000, f=6, b=8, n_nodes=4, k=2, seed=3):
        rng = np.random.default_rng(seed)
        Xb = jnp.asarray(rng.integers(0, b, size=(n, f)), jnp.int32)
        G = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
        H = jnp.asarray(rng.uniform(0.1, 1.0, size=n), jnp.float32)
        cu = jnp.asarray(H > 0, jnp.float32)
        node = jnp.asarray(rng.integers(0, n_nodes, size=n), jnp.int32)
        return Xb, G, H, cu, node, n_nodes, b

    def test_matmul_matches_segment(self):
        args = self._inputs()
        out_m = T._histograms_matmul(*args)
        out_s = T._histograms_segment(*args)
        for m, s in zip(out_m, out_s):
            assert np.allclose(np.asarray(m), np.asarray(s), atol=1e-3)

    def test_matmul_chunked_with_padding(self, monkeypatch):
        # force several chunks + a ragged tail (n=1000 with chunk=256)
        monkeypatch.setattr(T, "_HIST_CHUNK", 256)
        args = self._inputs(n=1000)
        out_m = T._histograms_matmul(*args)
        out_s = T._histograms_segment(*args)
        for m, s in zip(out_m, out_s):
            assert np.allclose(np.asarray(m), np.asarray(s), atol=1e-3)

    def test_grow_tree_matmul_path_matches(self, monkeypatch):
        """Full tree growth with the matmul histograms (as on TPU) produces
        the same splits and near-identical leaves as the segment path."""
        X, y = _xor_data(n=800, seed=7)
        edges = T.quantile_edges(jnp.asarray(X), 16)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        G = jnp.asarray((0.5 - y)[:, None], jnp.float32)
        H = jnp.full((len(y),), 0.25, jnp.float32)
        key = __import__("jax").random.PRNGKey(0)

        real_backend = T.jax.default_backend

        def grow(force_tpu):
            monkeypatch.setattr(
                T.jax, "default_backend",
                (lambda: "tpu") if force_tpu else real_backend)
            # bypass the jit cache: call the wrapped fn directly
            return T.grow_tree.__wrapped__(
                Xb, G, H, key, depth=3, n_bins=16, reg_lambda=1.0,
                leaf_mode="newton")

        t_mat = grow(True)
        t_seg = grow(False)
        assert np.array_equal(np.asarray(t_mat.feat), np.asarray(t_seg.feat))
        assert np.array_equal(np.asarray(t_mat.thresh),
                              np.asarray(t_seg.thresh))
        assert np.allclose(np.asarray(t_mat.leaf), np.asarray(t_seg.leaf),
                           atol=1e-4)

    def test_tpu_gather_free_paths_match(self, monkeypatch):
        """bin_matrix (edge counting), routing and prediction one-hot
        contractions — selected when backend=='tpu' — must equal the
        gather-based CPU lowerings exactly."""
        import jax
        X, y = _xor_data(n=700, seed=11)
        real_backend = T.jax.default_backend
        edges = T.quantile_edges(jnp.asarray(X), 16)

        monkeypatch.setattr(T.jax, "default_backend", lambda: "tpu")
        Xb_t = T.bin_matrix(jnp.asarray(X), edges)
        monkeypatch.setattr(T.jax, "default_backend", real_backend)
        Xb_c = T.bin_matrix(jnp.asarray(X), edges)
        assert np.array_equal(np.asarray(Xb_t), np.asarray(Xb_c))

        G = (0.5 - y)[:, None]
        H = jnp.full((len(y),), 0.25, jnp.float32)
        tree = T.grow_tree(Xb_c, jnp.asarray(G), H, __import__("jax").random.PRNGKey(3),
                           depth=4, n_bins=16, reg_lambda=1.0,
                           leaf_mode="newton")
        # routing parity
        node = jnp.asarray(np.random.default_rng(0).integers(0, 4, len(y)),
                           jnp.int32)
        f_lvl = tree.feat[3:7]
        t_lvl = tree.thresh[3:7]
        m_lvl = tree.miss[3:7]
        routed = T._route_level_matmul(Xb_c, node, f_lvl, t_lvl, m_lvl, 4)
        rows = jnp.arange(len(y))
        xb = Xb_c[rows, f_lvl[node]]
        expect = 2 * node + ((xb > t_lvl[node])
                             | ((xb == 0)
                                & (m_lvl[node] > 0))).astype(jnp.int32)
        assert np.array_equal(np.asarray(routed), np.asarray(expect))
        # prediction parity
        out_m = T._predict_bins_matmul(tree, Xb_c, 4)
        out_g = T.predict_bins(tree, Xb_c, 4)
        assert np.allclose(np.asarray(out_m), np.asarray(out_g), atol=1e-6)

    def test_route_chunk_padding(self, monkeypatch):
        monkeypatch.setattr(T, "_ROUTE_CHUNK", 128)
        X, y = _xor_data(n=500, seed=13)
        edges = T.quantile_edges(jnp.asarray(X), 8)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        node = jnp.asarray(np.random.default_rng(1).integers(0, 2, len(y)),
                           jnp.int32)
        f_lvl = jnp.asarray([1, 2], jnp.int32)
        t_lvl = jnp.asarray([3, 5], jnp.int32)
        m_lvl = jnp.asarray([0, 1], jnp.int32)
        routed = T._route_level_matmul(Xb, node, f_lvl, t_lvl, m_lvl, 2)
        rows = jnp.arange(len(y))
        xb = Xb[rows, f_lvl[node]]
        expect = 2 * node + ((xb > t_lvl[node])
                             | ((xb == 0)
                                & (m_lvl[node] > 0))).astype(jnp.int32)
        assert np.array_equal(np.asarray(routed), np.asarray(expect))

    def test_chunked_scan_boundary_full_fit(self, monkeypatch):
        """Full GBT fit through the forced-TPU path at N just past the
        histogram chunk boundary (chunked scan + sibling subtraction +
        one-hot routing all active) == the segment path's splits."""
        real_backend = T.jax.default_backend
        N, F, B = T._HIST_CHUNK + 1234, 8, 16
        rng = np.random.default_rng(21)
        X = rng.normal(size=(N, F)).astype(np.float32)
        y = (rng.uniform(size=N)
             < 1 / (1 + np.exp(-X @ np.linspace(1, -1, F)))).astype(
                 np.float32)
        w = jnp.ones(N, jnp.float32)
        edges = T.quantile_edges(jnp.asarray(X), B)
        Xb = T.bin_matrix(jnp.asarray(X), edges)
        key = __import__("jax").random.PRNGKey(2)

        def fit():
            return T.fit_gbt.__wrapped__(
                Xb, jnp.asarray(y), w, key, n_rounds=2, depth=4, n_bins=B,
                learning_rate=0.3, loss="logistic")

        monkeypatch.setattr(T.jax, "default_backend", lambda: "tpu")
        trees_t, base_t = fit()
        pred_t = np.asarray(T.predict_forest_bins(trees_t, Xb, 4))
        monkeypatch.setattr(T.jax, "default_backend", real_backend)
        trees_c, base_c = fit()
        pred_c = np.asarray(T.predict_forest_bins(trees_c, Xb, 4))
        assert np.array_equal(np.asarray(trees_t.feat),
                              np.asarray(trees_c.feat))
        assert np.allclose(pred_t, pred_c, atol=5e-3)
