"""Seeded random-workflow fuzz: the full pipeline over randomly drawn
feature-type combinations.

Each seed draws a random subset of feature types (numeric/text/
categorical/date/geo/map, with random missingness), builds a label
correlated with one numeric column, then runs transmogrify ->
SanityChecker -> BinaryClassificationModelSelector -> train -> score ->
save/load -> local row scoring, asserting structural invariants at every
step. This is the integration net the reference's ~250 suites cast over
hand-picked combinations, cast instead over random ones.
"""
import os
import tempfile

import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.local import score_function
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.prediction import probability_of
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.types import (
    Date, Geolocation, Integral, PickList, Real, RealMap, RealNN, Text,
)
from transmogrifai_tpu.workflow import Workflow
from transmogrifai_tpu.workflow.io import load_model


def _random_columns(rng, n):
    """(name, type, values, extractor-friendly raw values) pools."""
    cats = [f"c{i}" for i in range(rng.integers(2, 12))]
    words = ["ada", "bix", "cor", "dun", "eel", "fyr"]
    pool = {
        "num": (Real, [None if rng.uniform() < 0.15
                       else float(rng.normal()) for _ in range(n)]),
        "count": (Integral, [None if rng.uniform() < 0.1
                             else int(rng.integers(0, 50))
                             for _ in range(n)]),
        "cat": (PickList, [None if rng.uniform() < 0.1
                           else str(rng.choice(cats)) for _ in range(n)]),
        "txt": (Text, [None if rng.uniform() < 0.2 else " ".join(
            rng.choice(words, size=rng.integers(1, 5)))
            for _ in range(n)]),
        "ts": (Date, [None if rng.uniform() < 0.1 else int(
            1_500_000_000_000 + rng.integers(0, 10**9))
            for _ in range(n)]),
        "geo": (Geolocation, [None if rng.uniform() < 0.2 else
                              [float(rng.uniform(-90, 90)),
                               float(rng.uniform(-180, 180)), 1.0]
                              for _ in range(n)]),
        "mp": (RealMap, [{k: float(rng.normal())
                          for k in ("a", "b") if rng.uniform() > 0.2}
                         for _ in range(n)]),
    }
    return pool


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_workflow_end_to_end(seed):
    rng = np.random.default_rng(seed)
    n = 240
    pool = _random_columns(rng, n)
    # 2-5 random predictor columns, always at least one numeric driver
    names = ["num"] + list(rng.choice(
        [k for k in pool if k != "num"],
        size=int(rng.integers(1, 5)), replace=False))

    driver = np.array([v if v is not None else 0.0
                       for v in pool["num"][1]], np.float32)
    y = (driver + rng.normal(size=n) * 0.7 > 0).astype(np.float32)

    specs = [("label", RealNN, y.tolist())] + [
        (nm, pool[nm][0], pool[nm][1]) for nm in names]
    ds = Dataset.from_features(specs)

    fy = FeatureBuilder.RealNN("label").extract(
        lambda r: r.get("label")).as_response()
    feats = []
    for nm in names:
        t = pool[nm][0]
        builder = getattr(FeatureBuilder, t.__name__)(nm)
        feats.append(builder.extract(lambda r, _n=nm: r.get(_n))
                     .as_predictor())

    vec = transmogrify(feats)
    checked = SanityChecker(min_variance=1e-8).set_input(fy, vec) \
        .get_output()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        seed=int(seed),
        models_and_parameters=[
            (OpLogisticRegression(max_iter=10), [{"reg_param": 0.01}]),
            (OpGBTClassifier(max_iter=3, max_depth=3), [{}]),
        ]).set_input(fy, checked).get_output()

    model = Workflow().set_input_dataset(ds) \
        .set_result_features(pred).train()
    scored = model.score(ds)
    prob = probability_of(scored.column(pred.name))
    assert prob.shape == (n, 2)
    assert np.isfinite(prob).all()
    assert (prob >= 0).all() and (prob <= 1 + 1e-6).all()

    # the label is learnable from the numeric driver: better than chance
    auc_proxy = np.mean(prob[y == 1, 1]) - np.mean(prob[y == 0, 1])
    assert auc_proxy > 0.05, (names, auc_proxy)

    # save/load score parity
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m")
        model.save(path)
        m2 = load_model(path)
        prob2 = probability_of(m2.score(ds).column(pred.name))
        np.testing.assert_allclose(prob, prob2, atol=1e-5)

        # local row scoring agrees with batch on a few random rows
        fn = score_function(m2)
        for i in map(int, rng.integers(0, n, size=3)):
            row = {nm: pool[nm][1][i] for nm in names}
            row["label"] = float(y[i])
            out = fn(dict(row))[pred.name]
            rv = dict(out.value if hasattr(out, "value") else out)
            assert abs(float(rv["probability_1"]) - prob[i, 1]) < 1e-4, i
