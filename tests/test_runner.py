"""OpParams + OpWorkflowRunner/OpApp.

Mirrors reference suite core/src/test/.../OpWorkflowRunnerTest.scala:
Train/Score/Features/Evaluate run types, params round-trip, stage-param
overrides, metrics artifacts, app-end handlers.
"""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.evaluators.evaluators import (
    BinaryClassificationEvaluator)
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.workflow import (
    OpApp, OpParams, OpWorkflowRunner, ReaderParams, Workflow)


def _rows(n=300, seed=5):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        x = float(rng.normal())
        rows.append({"x": x, "y": float(rng.normal()),
                     "label": float(x + rng.normal(0, 0.5) > 0)})
    return rows


def _workflow():
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    fy = FeatureBuilder.Real("y").extract(lambda r: r.get("y")).as_predictor()
    fl = FeatureBuilder.RealNN("label").extract(
        lambda r: r.get("label")).as_response()
    vec = transmogrify([fx, fy])
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[
            (OpLogisticRegression(), param_grid(reg_param=[0.01]))],
    ).set_input(fl, vec).get_output()
    return Workflow().set_result_features(pred), vec


class TestOpParams:
    def test_json_file_round_trip(self, tmp_path):
        p = OpParams(stage_params={"SanityChecker": {"min_variance": 0.01}},
                     reader_params={"train": ReaderParams(path="/data")},
                     model_location="/m", custom_params={"tag": "run1"})
        path = str(tmp_path / "params.json")
        p.save(path)
        q = OpParams.from_file(path)
        assert q.stage_params == p.stage_params
        assert q.reader_params["train"].path == "/data"
        assert q.model_location == "/m"
        assert q.custom_params == {"tag": "run1"}

    def test_with_values(self):
        p = OpParams().with_values(model_location="/m2")
        assert p.model_location == "/m2"
        assert OpParams().model_location is None


class TestRunner:
    def test_train_then_score_then_evaluate(self, tmp_path):
        rows = _rows()
        wf, _ = _workflow()
        model_loc = str(tmp_path / "model")
        runner = OpWorkflowRunner(wf, train_reader=ListReader(rows),
                                  score_reader=ListReader(rows),
                                  evaluator=BinaryClassificationEvaluator())
        seen = []
        runner.add_application_end_handler(lambda r: seen.append(r.run_type))

        params = OpParams(model_location=model_loc,
                          write_location=str(tmp_path / "scores"),
                          metrics_location=str(tmp_path / "metrics"))
        tr = runner.run(OpWorkflowRunner.TRAIN, params)
        assert tr.run_type == "Train" and "Selected" in tr.model_summary
        assert os.path.isdir(model_loc)

        sc = runner.run(OpWorkflowRunner.SCORE, params)
        assert sc.n_rows == len(rows)
        assert sc.metrics.get("au_roc", 0) > 0.8
        assert os.path.exists(tmp_path / "scores" / "scores.jsonl")

        ev = runner.run(OpWorkflowRunner.EVALUATE, params)
        assert ev.metrics.get("au_roc", 0) > 0.8

        assert seen == ["Train", "Score", "Evaluate"]
        assert os.path.exists(tmp_path / "metrics" / "train_metrics.json")

    def test_features_run(self, tmp_path):
        rows = _rows()
        wf, vec = _workflow()
        runner = OpWorkflowRunner(wf, train_reader=ListReader(rows),
                                  features_to_compute=[vec])
        params = OpParams(write_location=str(tmp_path / "feat"))
        fr = runner.run(OpWorkflowRunner.FEATURES, params)
        assert fr.n_rows == len(rows)
        data = np.load(tmp_path / "feat" / "features.npz")
        assert any(k for k in data.files)

    def test_stage_param_overrides(self):
        rows = _rows()
        fx = FeatureBuilder.Real("x").extract(
            lambda r: r.get("x")).as_predictor()
        fl = FeatureBuilder.RealNN("label").extract(
            lambda r: r.get("label")).as_response()
        from transmogrifai_tpu.automl.preparators import SanityChecker
        vec = transmogrify([fx])
        checker = SanityChecker()
        checked = checker.set_input(fl, vec).get_output()
        wf = Workflow().set_result_features(checked)
        runner = OpWorkflowRunner(wf, train_reader=ListReader(rows))
        from transmogrifai_tpu.workflow.runner import apply_stage_params
        apply_stage_params(wf, OpParams(
            stage_params={"SanityChecker": {"min_variance": 0.5}}))
        assert checker.get_param("min_variance") == 0.5

    def test_unknown_run_type_raises(self):
        wf, _ = _workflow()
        runner = OpWorkflowRunner(wf)
        with pytest.raises(ValueError, match="Unknown run type"):
            runner.run("Bogus")


class TestOpApp:
    def test_main_dispatches(self, tmp_path):
        rows = _rows()

        class App(OpApp):
            def runner(self):
                wf, _ = _workflow()
                return OpWorkflowRunner(wf, train_reader=ListReader(rows))

        res = App().main(["--run-type", "Train",
                          "--model-location", str(tmp_path / "m")])
        assert res.run_type == "Train"
        assert os.path.isdir(tmp_path / "m")
