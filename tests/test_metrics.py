"""Metrics/tracing registry (reference OpSparkListener semantics)."""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.utils.metrics import MetricsCollector, collector
from transmogrifai_tpu.workflow import (
    OpParams, OpWorkflowRunner, Workflow)


def test_span_records_only_when_enabled():
    c = MetricsCollector()
    with c.span("s", "u", "fit", n_rows=5):
        pass
    assert c.current.stage_metrics == []
    c.enable("app")
    with c.span("s", "u", "fit", n_rows=5):
        pass
    app = c.finish()
    assert len(app.stage_metrics) == 1
    m = app.stage_metrics[0]
    assert m.phase == "fit" and m.n_rows == 5 and m.wall_seconds >= 0
    assert "Total:" in app.pretty()


def test_stats_pass_records_and_serializes():
    """collector.stats_pass: one call -> StatsPass record + a
    stats_pass[<driver>] kernel-roofline twin, both in to_json()."""
    c = MetricsCollector()
    assert c.stats_pass("fused", 100, 8, 2, 3200.0, 0.01) is None  # off
    c.enable("app")
    rec = c.stats_pass("fused", rows=100, cols=8, tiles=2,
                       bytes_hbm=3200.0, wall_seconds=0.01, cold=True)
    assert rec.driver == "fused" and rec.passes == 1
    app = c.finish()
    doc = app.to_json()
    assert doc["stats_metrics"][0]["rows"] == 100
    assert doc["stats_metrics"][0]["cold"] is True
    kernels = {k.kernel for k in app.kernel_metrics}
    assert "stats_pass[fused]" in kernels
    spans = [s for s in c.trace.spans if s.name == "stats_pass[fused]"]
    assert len(spans) == 1 and spans[0].attrs["tiles"] == 2


def test_workflow_run_collects_stage_metrics(tmp_path):
    rows = [{"x": float(i % 7), "y": float(i % 3)} for i in range(100)]
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    fy = FeatureBuilder.Real("y").extract(lambda r: r.get("y")).as_predictor()
    vec = transmogrify([fx, fy])
    wf = Workflow().set_result_features(vec)
    runner = OpWorkflowRunner(wf, train_reader=ListReader(rows))
    params = OpParams(collect_stage_metrics=True,
                      metrics_location=str(tmp_path))
    runner.run(OpWorkflowRunner.TRAIN, params)
    path = tmp_path / "train_stage_metrics.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert doc["stage_metrics"], "expected recorded spans"
    phases = {m["phase"] for m in doc["stage_metrics"]}
    assert "fit" in phases
    collector.disable()


class TestCustomEvaluator:
    def test_custom_metric_in_validator(self):
        import numpy as np
        from transmogrifai_tpu.automl.tuning.validators import CrossValidation
        from transmogrifai_tpu.evaluators.evaluators import Evaluators
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        from transmogrifai_tpu.models.prediction import positive_score_of

        def neg_brier(labels, pred_col, w):
            p = positive_score_of(pred_col)
            return -float(np.mean((p - np.asarray(labels)) ** 2))

        ev = Evaluators.custom("neg_brier", larger_better=True,
                               evaluate_fn=neg_brier)
        assert ev.is_larger_better()
        rng = np.random.default_rng(0)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        val = CrossValidation(ev, num_folds=3, seed=0)
        best = val.validate(
            [(OpLogisticRegression(max_iter=10),
              [{"reg_param": 0.01}, {"reg_param": 1.0}])], X, y)
        assert np.isfinite(best.best_metric)
        assert best.validated[0].metric_name == "neg_brier"
        # lower regularization should win on separable data
        assert best.best_grid["reg_param"] == 0.01


class TestLatencyHistogramMerge:
    """merge()/from_json() (the fleet telemetry substrate,
    docs/fleet.md): exact bucket-sum semantics — the fleet p99 from
    summed per-replica buckets must equal one histogram that recorded
    the union stream."""

    def _record(self, h, vals):
        for v in vals:
            h.record(float(v))

    def test_merge_equals_union_stream_quantiles(self):
        from transmogrifai_tpu.utils.metrics import LatencyHistogram
        rng = np.random.default_rng(7)
        for trial in range(5):
            xs = rng.lognormal(-6 + trial, 1.5, size=400)
            ys = rng.lognormal(-5, 0.5 + 0.3 * trial, size=250)
            a, b, u = (LatencyHistogram("t"), LatencyHistogram("t"),
                       LatencyHistogram("t"))
            self._record(a, xs)
            self._record(b, ys)
            self._record(u, list(xs) + list(ys))
            a.merge(b)
            assert a.count == u.count == 650
            # quantiles read only bucket counts + max: EXACT equality
            for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 0.999, 1.0):
                assert a.quantile(q) == u.quantile(q), (trial, q)
            assert a.max_seconds == u.max_seconds
            assert a.total_seconds == pytest.approx(u.total_seconds,
                                                    rel=1e-9)

    def test_merge_with_empty_is_identity(self):
        from transmogrifai_tpu.utils.metrics import LatencyHistogram
        rng = np.random.default_rng(3)
        h = LatencyHistogram("t")
        self._record(h, rng.lognormal(-6, 2, 100))
        before = h.to_json()
        h.merge(LatencyHistogram("empty"))
        assert h.to_json() == before
        # and the other direction: empty.merge(h) == h
        e = LatencyHistogram("t")
        e.merge(h)
        assert e.to_json() == before

    def test_from_json_roundtrip_bitexact(self):
        from transmogrifai_tpu.utils.metrics import LatencyHistogram
        rng = np.random.default_rng(11)
        h = LatencyHistogram("serve_total")
        self._record(h, rng.lognormal(-7, 2.5, 300))
        h.record(0.0)      # floor bucket
        h.record(5000.0)   # overflow bucket
        doc = h.to_json()
        r = LatencyHistogram.from_json(doc)
        assert r.to_json() == doc
        # merging two from_json copies doubles every bucket exactly
        r2 = LatencyHistogram.from_json(doc)
        r.merge(r2)
        assert r.count == 2 * h.count
        assert sum(r._counts) == 2 * sum(h._counts)

    def test_from_json_rejects_unknown_bucket(self):
        from transmogrifai_tpu.utils.metrics import LatencyHistogram
        with pytest.raises(ValueError):
            LatencyHistogram.from_json(
                {"name": "x", "count": 1, "mean_ms": 1.0, "max_ms": 1.0,
                 "buckets_ms": {"not-a-bucket": 1}})
