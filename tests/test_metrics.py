"""Metrics/tracing registry (reference OpSparkListener semantics)."""
import json
import os

import numpy as np

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.utils.metrics import MetricsCollector, collector
from transmogrifai_tpu.workflow import (
    OpParams, OpWorkflowRunner, Workflow)


def test_span_records_only_when_enabled():
    c = MetricsCollector()
    with c.span("s", "u", "fit", n_rows=5):
        pass
    assert c.current.stage_metrics == []
    c.enable("app")
    with c.span("s", "u", "fit", n_rows=5):
        pass
    app = c.finish()
    assert len(app.stage_metrics) == 1
    m = app.stage_metrics[0]
    assert m.phase == "fit" and m.n_rows == 5 and m.wall_seconds >= 0
    assert "Total:" in app.pretty()


def test_stats_pass_records_and_serializes():
    """collector.stats_pass: one call -> StatsPass record + a
    stats_pass[<driver>] kernel-roofline twin, both in to_json()."""
    c = MetricsCollector()
    assert c.stats_pass("fused", 100, 8, 2, 3200.0, 0.01) is None  # off
    c.enable("app")
    rec = c.stats_pass("fused", rows=100, cols=8, tiles=2,
                       bytes_hbm=3200.0, wall_seconds=0.01, cold=True)
    assert rec.driver == "fused" and rec.passes == 1
    app = c.finish()
    doc = app.to_json()
    assert doc["stats_metrics"][0]["rows"] == 100
    assert doc["stats_metrics"][0]["cold"] is True
    kernels = {k.kernel for k in app.kernel_metrics}
    assert "stats_pass[fused]" in kernels
    spans = [s for s in c.trace.spans if s.name == "stats_pass[fused]"]
    assert len(spans) == 1 and spans[0].attrs["tiles"] == 2


def test_workflow_run_collects_stage_metrics(tmp_path):
    rows = [{"x": float(i % 7), "y": float(i % 3)} for i in range(100)]
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    fy = FeatureBuilder.Real("y").extract(lambda r: r.get("y")).as_predictor()
    vec = transmogrify([fx, fy])
    wf = Workflow().set_result_features(vec)
    runner = OpWorkflowRunner(wf, train_reader=ListReader(rows))
    params = OpParams(collect_stage_metrics=True,
                      metrics_location=str(tmp_path))
    runner.run(OpWorkflowRunner.TRAIN, params)
    path = tmp_path / "train_stage_metrics.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert doc["stage_metrics"], "expected recorded spans"
    phases = {m["phase"] for m in doc["stage_metrics"]}
    assert "fit" in phases
    collector.disable()


class TestCustomEvaluator:
    def test_custom_metric_in_validator(self):
        import numpy as np
        from transmogrifai_tpu.automl.tuning.validators import CrossValidation
        from transmogrifai_tpu.evaluators.evaluators import Evaluators
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        from transmogrifai_tpu.models.prediction import positive_score_of

        def neg_brier(labels, pred_col, w):
            p = positive_score_of(pred_col)
            return -float(np.mean((p - np.asarray(labels)) ** 2))

        ev = Evaluators.custom("neg_brier", larger_better=True,
                               evaluate_fn=neg_brier)
        assert ev.is_larger_better()
        rng = np.random.default_rng(0)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        val = CrossValidation(ev, num_folds=3, seed=0)
        best = val.validate(
            [(OpLogisticRegression(max_iter=10),
              [{"reg_param": 0.01}, {"reg_param": 1.0}])], X, y)
        assert np.isfinite(best.best_metric)
        assert best.validated[0].metric_name == "neg_brier"
        # lower regularization should win on separable data
        assert best.best_grid["reg_param"] == 0.01
