"""Metrics/tracing registry (reference OpSparkListener semantics)."""
import json
import os

import numpy as np

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.utils.metrics import MetricsCollector, collector
from transmogrifai_tpu.workflow import (
    OpParams, OpWorkflowRunner, Workflow)


def test_span_records_only_when_enabled():
    c = MetricsCollector()
    with c.span("s", "u", "fit", n_rows=5):
        pass
    assert c.current.stage_metrics == []
    c.enable("app")
    with c.span("s", "u", "fit", n_rows=5):
        pass
    app = c.finish()
    assert len(app.stage_metrics) == 1
    m = app.stage_metrics[0]
    assert m.phase == "fit" and m.n_rows == 5 and m.wall_seconds >= 0
    assert "Total:" in app.pretty()


def test_workflow_run_collects_stage_metrics(tmp_path):
    rows = [{"x": float(i % 7), "y": float(i % 3)} for i in range(100)]
    fx = FeatureBuilder.Real("x").extract(lambda r: r.get("x")).as_predictor()
    fy = FeatureBuilder.Real("y").extract(lambda r: r.get("y")).as_predictor()
    vec = transmogrify([fx, fy])
    wf = Workflow().set_result_features(vec)
    runner = OpWorkflowRunner(wf, train_reader=ListReader(rows))
    params = OpParams(collect_stage_metrics=True,
                      metrics_location=str(tmp_path))
    runner.run(OpWorkflowRunner.TRAIN, params)
    path = tmp_path / "train_stage_metrics.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert doc["stage_metrics"], "expected recorded spans"
    phases = {m["phase"] for m in doc["stage_metrics"]}
    assert "fit" in phases
    collector.disable()
