"""Parity: the _tmog_pyext C loops vs their pure-Python fallbacks.

Every pyext entry point must produce byte-identical results to the numpy/
python path it accelerates (the fallback stays live for builds without a
compiler), so each case computes both and compares. Reference anchor for
the semantics under test: the fused row-map transforms of
core/.../utils/stages/FitStagesUtil.scala:96 (one-hot codes, map key
explosion, float coercion) — here exercised at the encoding layer.
"""
from __future__ import annotations

import math

import numpy as np
import pytest

from transmogrifai_tpu.ops import pyext_bridge as px


pytestmark = pytest.mark.skipif(px.module() is None,
                                reason="C extension unavailable")


MIXED = ["a", None, "b", "a", 1, 1.0, True, float("nan"), "ω", "", "b"]


def test_pack_strings_matches_manual_encoding():
    buf, off = px.pack_strings(MIXED)
    strs = ["" if v is None else (v if type(v) is str else str(v))
            for v in MIXED]
    enc = [s.encode("utf-8", errors="surrogatepass") for s in strs]
    joined = b"".join(enc)
    assert bytes(buf[:len(joined)]) == joined
    lens = np.diff(off)
    assert lens.tolist() == [len(b) for b in enc]


def test_pack_strings_surrogates():
    s = "x\udcff y"  # surrogateescape leftover must pack, not crash
    buf, off = px.pack_strings([s])
    assert bytes(buf[:off[1]]) == s.encode("utf-8", errors="surrogatepass")


def test_dict_encode_first_occurrence_order():
    codes, uniques = px.dict_encode(MIXED)
    # python reference: same stringification, first-occurrence order
    seen = {}
    ref_codes = []
    for v in MIXED:
        s = "" if v is None else (v if type(v) is str else str(v))
        ref_codes.append(seen.setdefault(s, len(seen)))
    assert codes.tolist() == ref_codes
    assert uniques == list(dict.fromkeys(
        "" if v is None else (v if type(v) is str else str(v))
        for v in MIXED))


def test_pivot_codes_matches_python_semantics():
    from transmogrifai_tpu.automl.vectorizers.encoding import (
        pivot_block_single,
    )
    vocab = ["a", "b", "1.0"]
    clean = str.lower

    data = ["A", "b", None, float("nan"), "A", 1.0, 1, True, {}, "zz"]
    # C path (through pivot_block_single's fast route)
    got = pivot_block_single(data, vocab, True, clean)
    # forced python path
    import transmogrifai_tpu.ops.pyext_bridge as bridge
    orig = bridge.pivot_codes
    bridge.pivot_codes = lambda *a, **k: None
    try:
        want = pivot_block_single(data, vocab, True, clean)
    finally:
        bridge.pivot_codes = orig
    np.testing.assert_array_equal(got, want)


def test_extract_key_columns_parity_both_clean_modes():
    from transmogrifai_tpu.automl.vectorizers import encoding

    rows = [{"k0": 1.5, "K0": 9.0, "other": 2}, None, {}, {"k1": "x"},
            {"k0": None, "k1": 3}]
    keys = ["k0", "k1"]
    for clean_fn in (None, str.lower):
        got = px.extract_key_columns(rows, keys, clean_fn)
        import transmogrifai_tpu.ops.pyext_bridge as bridge
        orig = bridge.extract_key_columns
        bridge.extract_key_columns = lambda *a, **k: None
        try:
            want = encoding.extract_key_columns(rows, keys, clean_fn)
        finally:
            bridge.extract_key_columns = orig
        assert got == want


def test_extract_key_columns_rejects_duplicate_keys():
    """The C layer enforces the no-duplicate invariant itself: a
    duplicate key would make PyDict_SetItem free an earlier column while
    the C loop still holds its borrowed pointer (ADVICE r5)."""
    with pytest.raises(ValueError, match="duplicate key"):
        px.extract_key_columns([{"k0": 1}], ["k0", "k1", "k0"], None)


def test_float_column_parity_incl_numeric_strings():
    vals = [1, None, 2.5, True, "3.5", np.float64(7)]
    got = px.float_column(vals, -9.0)
    want = np.fromiter(
        (-9.0 if v is None else float(v) for v in vals), np.float64,
        len(vals))
    np.testing.assert_array_equal(got, want)


def test_float_column_bad_string_raises():
    with pytest.raises((TypeError, ValueError)):
        px.float_column(["not-a-number"], 0.0)


def test_masks_and_ascii():
    data = ["", None, "x", [], [1], 0, 1]
    np.testing.assert_array_equal(
        px.null_mask(data), [v is None for v in data])
    np.testing.assert_array_equal(
        px.empty_mask(data), [not v for v in data])
    assert px.all_ascii(["abc", None, "x y"]) is True
    assert px.all_ascii(["abc", "ω"]) is False
    assert px.all_ascii([1]) is False  # non-str: python path decides


def test_sink_fusion_score_matches_blockwise_concat():
    """model.score's sink-fused matrix == concat of per-stage blocks."""
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.types import PickList, Real, Text
    from transmogrifai_tpu.workflow.workflow import Workflow

    rng = np.random.default_rng(3)
    n = 400
    rows = {
        "pl": [None if i % 7 == 0 else f"c{i % 9}" for i in range(n)],
        "tx": [None if i % 5 == 0 else
               f"w{rng.integers(0, 200)} w{rng.integers(0, 200)}"
               for i in range(n)],
        "r": [None if i % 11 == 0 else float(rng.normal())
              for i in range(n)],
    }
    ds = Dataset.from_features([
        ("pl", PickList, rows["pl"]),
        ("tx", Text, rows["tx"]),
        ("r", Real, rows["r"]),
    ])
    feats = [
        FeatureBuilder.PickList("pl").extract(
            lambda r: r.get("pl")).as_predictor(),
        FeatureBuilder.Text("tx").extract(
            lambda r: r.get("tx")).as_predictor(),
        FeatureBuilder.Real("r").extract(
            lambda r: r.get("r")).as_predictor(),
    ]
    vec = transmogrify(feats)
    model = Workflow().set_input_dataset(ds).set_result_features(vec).train()
    scored = model.score(ds).column(vec.name)

    # independent reassembly: every fitted vectorizer's transform_columns
    # (the unfused path), concatenated in combiner input order
    from transmogrifai_tpu.automl.vectorizers.combiner import VectorsCombiner
    comb = next(st for st in model.stages if isinstance(st, VectorsCombiner))
    full = model.transform(ds)
    by_name = {st.output_name(): st for st in model.stages}
    parts = []
    for name in comb.input_names():
        st = by_name[name]
        cols = [full.column(c) for c in st.input_names()]
        parts.append(np.asarray(st.transform_columns(*cols).data))
    want = np.concatenate(parts, axis=1)
    np.testing.assert_array_equal(np.asarray(scored.data), want)
    assert scored.metadata is not None
    assert scored.metadata.size == want.shape[1]


def test_sink_fusion_survives_producer_failure(monkeypatch):
    """A producer whose in-place write blows up must fall back loudly-
    but-correctly: the combiner re-copies its block over the dead view
    and the final matrix is unchanged vs the unfused reassembly."""
    import numpy as np

    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.automl.vectorizers.categorical import OneHotModel
    from transmogrifai_tpu.types import PickList, Real
    from transmogrifai_tpu.workflow.workflow import Workflow

    n = 200
    rows = {
        "pl": [f"c{i % 5}" if i % 9 else None for i in range(n)],
        "r": [float(i % 7) if i % 4 else None for i in range(n)],
    }
    ds = Dataset.from_features([
        ("pl", PickList, rows["pl"]),
        ("r", Real, rows["r"]),
    ])
    feats = [
        FeatureBuilder.PickList("pl").extract(
            lambda r: r.get("pl")).as_predictor(),
        FeatureBuilder.Real("r").extract(
            lambda r: r.get("r")).as_predictor(),
    ]
    vec = transmogrify(feats)
    model = Workflow().set_input_dataset(ds).set_result_features(
        vec).train()
    want = np.asarray(model.score(ds).column(vec.name).data)

    orig = OneHotModel.transform_block_into

    def boom(self, cols, out):
        if out.base is not None:   # the planned combiner-slice view:
            out[:, :1] = 1.0       # partial garbage write, then die
            raise RuntimeError("forced producer failure")
        return orig(self, cols, out)   # own buffer: behave (the
        # transform_block fallback route)

    monkeypatch.setattr(OneHotModel, "transform_block_into", boom)
    got = np.asarray(model.score(ds).column(vec.name).data)
    np.testing.assert_array_equal(got, want)


def test_pyext_fuzz_parity_random_object_soup():
    """Property-style: random mixed-type columns through every C loop vs
    its Python fallback — parity must hold on soup, not just curated
    cases."""
    import transmogrifai_tpu.ops.pyext_bridge as bridge
    from transmogrifai_tpu.automl.vectorizers import encoding

    rng = np.random.default_rng(123)
    pool = ["a", "B", "", None, 0, 1, -3, 2.5, float("nan"), True, False,
            "ω", "x y", 1.0, "1.0", "  pad  ", 10**20]
    for trial in range(5):
        data = [pool[i] for i in rng.integers(0, len(pool), size=300)]

        got_codes, got_uniq = px.dict_encode(data)
        seen = {}
        ref = [seen.setdefault(
            "" if v is None else (v if type(v) is str else str(v)),
            len(seen)) for v in data]
        assert got_codes.tolist() == ref, trial

        np.testing.assert_array_equal(
            px.null_mask(data), [v is None for v in data])
        np.testing.assert_array_equal(
            px.empty_mask(data), [not v for v in data])

        vocab = ["a", "b", "1.0", "x y"]
        got = encoding.pivot_block_single(data, vocab, True, str.lower)
        orig = bridge.pivot_codes
        bridge.pivot_codes = lambda *a, **k: None
        try:
            want = encoding.pivot_block_single(data, vocab, True,
                                               str.lower)
        finally:
            bridge.pivot_codes = orig
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")

        nums = [v for v in data
                if v is None or isinstance(v, (int, float, bool))]
        got_f = px.float_column(nums, -1.0)
        want_f = np.fromiter(
            (-1.0 if v is None else float(v) for v in nums),
            np.float64, len(nums))
        np.testing.assert_array_equal(got_f, want_f)
