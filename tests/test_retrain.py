"""Drift-triggered continuous retraining (retrain/, docs/retraining.md).

Fast tier: the RetrainController state machine against FAKE launchers
and rollout managers (every transition; every injected fault class ends
QUARANTINED/COOLDOWN with the champion byte-untouched and the rollout
never started), journal crash-resume (a handcrafted journal killed
between each pair of adjacent states resumes with exactly one rollout),
trigger debounce (window_id dedupe, stale-model hash, cooldown, storm
breaker), EventLog.follow across size rotation, the drift_alert payload
regression (window_id + model_content_hash), the across-time GLM warm
seed, the refit worker run in-process with a real tiny model, and the
fleet HTTP surface (POST /retrain 409 mirror of RolloutConflict).
"""
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu.retrain import refit as RF
from transmogrifai_tpu.retrain.controller import (COOLDOWN, FITTING,
                                                  QUARANTINED,
                                                  ROLLING_OUT, TRIGGERED,
                                                  VALIDATING,
                                                  RetrainConflict,
                                                  RetrainController,
                                                  RetrainPolicy)
from transmogrifai_tpu.retrain.journal import RetrainJournal
from transmogrifai_tpu.utils.tracing import EventLog, follow_events
from transmogrifai_tpu.workflow.io import model_content_hash

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# shared tiny champion model (real artifact: the validation gate LOADS it)
# ---------------------------------------------------------------------------

def _make_rows(n, seed=0, shift=0.0):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a, b = float(rng.normal(shift)), float(rng.normal())
        rows.append({"a": a, "b": b, "y": float(a + 0.5 * b > shift)})
    return rows


def _fit_and_save(rows, out_dir):
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.workflow import Workflow

    fa = FeatureBuilder.Real("a").extract(
        lambda r: r.get("a")).as_predictor()
    fb = FeatureBuilder.Real("b").extract(
        lambda r: r.get("b")).as_predictor()
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(max_iter=10),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify([fa, fb])).get_output()
    model = Workflow().set_reader(ListReader(rows)) \
        .set_result_features(pred).train()
    model.save(out_dir)
    return model


@pytest.fixture(scope="module")
def champion(tmp_path_factory):
    d = tmp_path_factory.mktemp("retrain_champion")
    out = str(d / "model")
    _fit_and_save(_make_rows(300, seed=0), out)
    return out


def _dir_hashes(path):
    out = {}
    for name in sorted(os.listdir(path)):
        p = os.path.join(path, name)
        if os.path.isfile(p):
            with open(p, "rb") as fh:
                out[name] = hash(fh.read())
    return out


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------

class FakeProc:
    def __init__(self, rc=0, hang=False):
        self._lk = threading.Lock()
        self._rc = rc
        self.hang = hang
        self.killed = False

    def poll(self):
        with self._lk:
            if self.hang and not self.killed:
                return None
            return -9 if self.killed else self._rc

    def wait(self, timeout=None):
        return self.poll()

    def kill(self):
        with self._lk:
            self.killed = True


class FakeLauncher:
    """Per-attempt worker behaviors, producing real candidate artifacts
    (the gate loads them) without a subprocess."""

    def __init__(self, champion_dir, behaviors=("ok",),
                 cand_metric=0.9, champ_metric=0.9):
        self.champion_dir = champion_dir
        self.behaviors = list(behaviors)
        self.cand_metric = cand_metric
        self.champ_metric = champ_metric
        self.calls = 0
        self.block = None  # threading.Event -> hold the "worker" open

    def _write_candidate(self, spec, corrupt=False, no_monitor=False,
                         metric=None):
        out = spec.out_dir
        if os.path.isdir(out):
            shutil.rmtree(out)
        shutil.copytree(self.champion_dir, out)
        for extra in ("serve.json",):
            p = os.path.join(out, extra)
            if os.path.exists(p):
                os.remove(p)
        if corrupt:
            with open(os.path.join(out, "op-model.json"), "w") as fh:
                fh.write("{corrupt")
        if no_monitor:
            os.remove(os.path.join(out, "monitor.json"))
        report = {
            "candidate_hash": model_content_hash(out),
            "champion_hash": model_content_hash(spec.champion_dir),
            "metric": "au_pr", "metric_larger_better": True,
            "candidate_metric": (self.cand_metric if metric is None
                                 else metric),
            "champion_metric": self.champ_metric,
            "train_rows": 100, "holdout_rows": 20,
        }
        with open(os.path.join(out, RF.REPORT_JSON), "w") as fh:
            json.dump(report, fh)

    def __call__(self, spec_path):
        spec = RF.RefitSpec.load(spec_path)
        b = self.behaviors[min(self.calls, len(self.behaviors) - 1)]
        self.calls += 1
        if self.block is not None:
            self.block.wait(30.0)
        if b == "ok":
            self._write_candidate(spec)
            return FakeProc(0)
        if b == "crash":
            return FakeProc(13)
        if b == "hang":
            return FakeProc(hang=True)
        if b == "bad_artifact":
            self._write_candidate(spec, corrupt=True)
            return FakeProc(0)
        if b == "no_monitor":
            self._write_candidate(spec, no_monitor=True)
            return FakeProc(0)
        if b == "low_metric":
            self._write_candidate(spec, metric=0.1)
            return FakeProc(0)
        raise AssertionError(f"unknown behavior {b}")


class FakeRollout:
    def __init__(self, outcome="swapped", delay=0.0):
        self._lk = threading.Lock()
        self.outcome = outcome
        self.delay = delay
        self.start_calls = []
        self.aborted = 0
        self._state = "idle"
        self._t0 = None
        self.last_verdict = None

    def start(self, model_dir, fraction=0.2, min_shadow=64,
              replicas=None, **kw):
        with self._lk:
            self.start_calls.append(model_dir)
            self.start_kwargs = dict(kw, fraction=fraction,
                                     min_shadow=min_shadow)
            self._state = "shadow"
            self._t0 = time.monotonic()
            return {"state": self._state}

    def status(self):
        with self._lk:
            if self._state == "shadow" and \
                    time.monotonic() - self._t0 >= self.delay:
                self._state = self.outcome
                self.last_verdict = {
                    "clean": self.outcome == "swapped",
                    "reasons": [] if self.outcome == "swapped"
                    else ["score_shift 0.5 > 0.2"]}
            return {"state": self._state,
                    "last_verdict": self.last_verdict}

    def abort(self):
        with self._lk:
            self.aborted += 1
            self._state = "rejected"
            # mirror RolloutManager.abort's operator marker — the
            # controller tells "failed at traffic" from "aborted" by it
            self.last_verdict = {"clean": False, "reasons": ["aborted"],
                                 "aborted": True}

    def set_delay(self, v):
        with self._lk:  # status() reads delay under this lock
            self.delay = v


def _controller(champion_dir, root, launcher, rollout,
                cls=RetrainController, recipe="default", **policy_kw):
    kw = dict(min_interval_s=0.0, storm_window_s=3600.0,
              max_retrains_per_window=100, fit_timeout_s=5.0,
              fit_attempts=2, backoff_base_s=0.01, backoff_cap_s=0.02,
              metric_tolerance=0.02, require_monitor_green=False,
              rollout_timeout_s=10.0,
              # in-process artifact probe: the fake-driven suite stays
              # fast; the sandboxed child path has its own test + the
              # ci.sh fault smoke
              sandbox_load_probe=False)
    kw.update(policy_kw)
    if recipe == "default":
        recipe = {"builder": "nope:nope", "history": []}
    return cls(
        lambda: champion_dir, root=str(root), rollout=rollout,
        policy=RetrainPolicy(**kw), recipe=recipe, launcher=launcher)


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

class TestJournal:
    def test_roundtrip_and_seq_continues_on_reopen(self, tmp_path):
        p = str(tmp_path / "j" / "journal.jsonl")
        j = RetrainJournal(p)
        j.append("c1", TRIGGERED, cycle_dir="/x")
        j.append("c1", FITTING, attempt=1)
        j.close()
        j2 = RetrainJournal(p)
        j2.append("c1", VALIDATING)
        recs = j2.records()
        assert [r["seq"] for r in recs] == [0, 1, 2]
        assert [r["state"] for r in recs] == [TRIGGERED, FITTING,
                                              VALIDATING]
        cid, crecs = j2.last_cycle()
        assert cid == "c1" and len(crecs) == 3
        j2.close()

    def test_torn_last_line_skipped(self, tmp_path):
        p = str(tmp_path / "journal.jsonl")
        j = RetrainJournal(p)
        j.append("c1", TRIGGERED)
        j.append("c1", FITTING)
        j.close()
        with open(p, "a") as fh:
            fh.write('{"seq": 2, "cycle": "c1", "state": "valid')  # torn
        j2 = RetrainJournal(p)
        recs = j2.records()
        assert [r["state"] for r in recs] == [TRIGGERED, FITTING]
        # a new append continues past the torn line's seq space cleanly
        j2.append("c1", VALIDATING)
        assert j2.records()[-1]["seq"] == 2
        j2.close()

    def test_last_cycle_picks_latest(self, tmp_path):
        j = RetrainJournal(str(tmp_path / "journal.jsonl"))
        j.append("c1", TRIGGERED)
        j.append("c1", COOLDOWN)
        j.append("c2", TRIGGERED)
        cid, recs = j.last_cycle()
        assert cid == "c2" and len(recs) == 1
        j.close()


# ---------------------------------------------------------------------------
# EventLog.follow (satellite 1)
# ---------------------------------------------------------------------------

class TestFollowEvents:
    def _collect(self, path, n, from_start=True, timeout=10.0):
        stop = threading.Event()
        got = []
        gen = follow_events(path, stop=stop, poll_s=0.01,
                            from_start=from_start)
        deadline = time.monotonic() + timeout
        for rec in gen:
            got.append(rec)
            if len(got) >= n:
                stop.set()
            if time.monotonic() > deadline:
                stop.set()
        return got

    def test_follow_yields_existing_and_new(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        log = EventLog(p, max_mb=0)
        for i in range(5):
            log.emit("tick", i=i)
        got = self._collect(p, 5)
        assert [r["i"] for r in got] == list(range(5))
        log.close()

    def test_follow_across_rotation_is_seq_monotone(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        # ~1KB threshold: many rotations over 120 events (keep is
        # generous so no segment drops — drop semantics are tail -f's)
        log = EventLog(p, max_mb=0.001, keep=40)
        for i in range(120):
            log.emit("tick", i=i, pad="x" * 60)
        assert log.rotations > 0
        got = self._collect(p, 120)
        seqs = [r["seq"] for r in got]
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs) == 120  # exactly once each
        assert [r["i"] for r in got] == list(range(120))
        log.close()

    def test_follow_live_rotation_mid_stream(self, tmp_path):
        """Events emitted WHILE following, with rotations happening
        between polls, arrive exactly once and in order."""
        p = str(tmp_path / "events.jsonl")
        log = EventLog(p, max_mb=0.001, keep=40)
        stop = threading.Event()
        got = []

        def consume():
            for rec in follow_events(p, stop=stop, poll_s=0.005,
                                     from_start=True):
                got.append(rec)
                if len(got) >= 80:
                    stop.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        for i in range(80):
            log.emit("tick", i=i, pad="y" * 60)
            if i % 7 == 0:
                time.sleep(0.01)
        t.join(15.0)
        stop.set()
        assert not t.is_alive()
        assert [r["i"] for r in got] == list(range(80))
        assert log.rotations > 0
        log.close()

    def test_follow_truncate_in_place_rescans(self, tmp_path):
        """logrotate-copytruncate semantics: the file is truncated
        UNDER the follower with its inode intact, leaving the byte
        offset past the new EOF — that must trigger the same rescan a
        replaced inode does, not wedge the tail forever."""
        p = str(tmp_path / "events.jsonl")
        log = EventLog(p, max_mb=0)
        for i in range(3):
            log.emit("tick", i=i)
        stop = threading.Event()
        got = []

        def consume():
            for rec in follow_events(p, stop=stop, poll_s=0.005,
                                     from_start=True):
                got.append(rec)
                if len(got) >= 6:
                    stop.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        _wait(lambda: len(got) == 3, msg="pre-truncate tail")
        with open(p, "r+", encoding="utf-8") as fh:
            fh.truncate(0)  # same inode, size 0
        # the shrink must be OBSERVABLE at a poll boundary (tail -F's
        # contract too): give the follower a few polls before the log
        # refills past the stale offset
        time.sleep(0.05)
        for i in range(3, 6):
            # the writer's append-mode handle lands at the new EOF and
            # seq keeps growing, so the rescan's seq filter still holds
            log.emit("tick", i=i)
        t.join(15.0)
        stop.set()
        assert not t.is_alive()
        assert [r["i"] for r in got] == list(range(6))
        log.close()

    def test_from_start_false_skips_history(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        log = EventLog(p, max_mb=0)
        log.emit("old", i=0)
        stop = threading.Event()
        got = []

        def consume():
            for rec in follow_events(p, stop=stop, poll_s=0.01,
                                     from_start=False):
                got.append(rec)
                stop.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.1)
        log.emit("new", i=1)
        t.join(10.0)
        assert [r["event"] for r in got] == ["new"]
        log.close()


# ---------------------------------------------------------------------------
# drift_alert payload: window_id + model_content_hash (satellite 2)
# ---------------------------------------------------------------------------

class TestAlertPayload:
    def test_profile_stamps_model_hash(self, champion):
        from transmogrifai_tpu.monitor.profile import ReferenceProfile
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        prof = ReferenceProfile.from_json(load_monitor_profile(champion))
        assert prof.model_hash == model_content_hash(champion)
        # roundtrip preserves it
        prof2 = ReferenceProfile.from_json(prof.to_json())
        assert prof2.model_hash == prof.model_hash

    def _profile(self):
        from transmogrifai_tpu.monitor.profile import (FeatureProfile,
                                                       ReferenceProfile)
        return ReferenceProfile(
            bins=8, rows=100.0, model_hash="abc123",
            features=[FeatureProfile(
                name="a", kind="numeric", count=100.0, nulls=0.0,
                hist=[12.5] * 8, lo=0.0, hi=1.0)])

    def test_alerts_of_one_window_share_a_stable_window_id(self):
        from transmogrifai_tpu.monitor.window import ServeMonitor
        prof = self._profile()
        mon = ServeMonitor(prof, window_rows=64, window_seconds=1e9)
        # shifted mass: everything in the top bin -> JS + PSI alerts
        X = np.full((64, 1), 0.99, np.float32)
        mon.observe_numeric(X, np.ones(64, np.float32))
        mon.add_rows(64)
        rep = mon.last_report
        assert rep is not None and rep["alerts"]
        assert rep["window_id"].startswith("abc123:")
        assert rep["window_id"].endswith(":w0")
        assert rep["model_content_hash"] == "abc123"
        # a second monitor over the same profile mints a DIFFERENT
        # window id for ITS window 0 (replicas must not dedupe away
        # each other's alerts)
        mon2 = ServeMonitor(prof, window_rows=64, window_seconds=1e9)
        assert mon2.window_id(0) != mon.window_id(0)

    def test_pooled_fleet_drift_carries_identity(self):
        from transmogrifai_tpu.fleet import telemetry as FT
        from transmogrifai_tpu.monitor.window import ServeMonitor
        prof = self._profile()
        mon = ServeMonitor(prof, window_rows=10 ** 9, window_seconds=1e9)
        X = np.full((40, 1), 0.99, np.float32)
        mon.observe_numeric(X, np.ones(40, np.float32))
        mon.add_rows(40)
        pooled = FT.fleet_drift(prof, [mon.window_state()])
        # <model_hash>:fleet-<monitor-nonce digest>:w<index> — the tag
        # keeps a restarted replica's (or fleet's) pooled "w0" from
        # colliding with dedupe/quarantine state recorded against a
        # previous incarnation's windows
        wid = pooled["pooled"]["window_id"]
        assert wid.startswith("abc123:fleet-") and wid.endswith(":w0")
        assert pooled["pooled"]["model_content_hash"] == "abc123"
        # deterministic across polls of the same open window
        pooled2 = FT.fleet_drift(prof, [mon.window_state()])
        assert pooled2["pooled"]["window_id"] == wid
        # a restarted replica = a FRESH monitor = a new id namespace,
        # even though its window_index restarts at the same 0
        mon2 = ServeMonitor(prof, window_rows=10 ** 9,
                            window_seconds=1e9)
        mon2.add_rows(1)
        pooled3 = FT.fleet_drift(prof, [mon2.window_state()])
        assert pooled3["pooled"]["window_id"] != wid
        assert pooled3["pooled"]["window_id"].endswith(":w0")

    def test_double_trigger_regression(self, champion, tmp_path):
        """THE regression: two alerts for one window start ONE cycle."""
        launcher = FakeLauncher(champion)
        launcher.block = threading.Event()  # hold the cycle in FITTING
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        alert = {"window_id": "h:m:w3", "target": "a", "metric": "js",
                 "model_content_hash": model_content_hash(champion)}
        try:
            assert ctl.handle_alert(dict(alert)) is None  # triggered
            assert ctl.handle_alert(dict(alert)) == "duplicate"
            # same window, different feature -> the cycle is busy, not
            # a second trigger
            other = dict(alert, target="b")
            assert ctl.handle_alert(other) == "busy"
            assert ctl.cycles_total == 1
        finally:
            launcher.block.set()
            _wait(lambda: ctl.effective_state() == "idle", msg="cycle")
            ctl.close()

    def test_stale_model_alert_ignored(self, champion, tmp_path):
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout())
        stale = {"window_id": "h:m:w9", "target": "a", "metric": "js",
                 "model_content_hash": "deadbeefdeadbeef"}
        try:
            assert ctl.handle_alert(stale) == "stale_model"
            assert ctl.cycles_total == 0
        finally:
            ctl.close()


# ---------------------------------------------------------------------------
# controller state machine vs fakes
# ---------------------------------------------------------------------------

class TestControllerStateMachine:
    def test_happy_path_swaps(self, champion, tmp_path):
        launcher = FakeLauncher(champion)
        ro = FakeRollout(outcome="swapped")
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger(reason="manual")
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert ro.start_calls and launcher.calls == 1
            states = [r["state"] for r in ctl.journal.records()]
            assert states == [TRIGGERED, FITTING, VALIDATING,
                              ROLLING_OUT, COOLDOWN]
            assert ctl.last_verdict["outcome"] == "swapped"
            assert ctl.quarantined_total == 0
        finally:
            ctl.close()

    def test_concurrent_trigger_conflicts(self, champion, tmp_path):
        launcher = FakeLauncher(champion)
        launcher.block = threading.Event()
        ctl = _controller(champion, tmp_path / "r", launcher,
                          FakeRollout())
        try:
            ctl.trigger()
            with pytest.raises(RetrainConflict):
                ctl.trigger()
        finally:
            launcher.block.set()
            _wait(lambda: ctl.effective_state() == "idle", msg="cycle")
            ctl.close()

    def _assert_contained(self, ctl, ro, champion, pre_hashes, reason):
        assert ctl.quarantined_total == 1
        assert ro.start_calls == [], "rollout must never see the " \
                                     "candidate"
        assert _dir_hashes(champion) == pre_hashes, "champion touched!"
        q = ctl.quarantine_list()
        assert len(q) == 1 and reason in q[0]["reason"]
        assert os.path.isdir(q[0]["dir"]), "evidence dir missing"
        states = [r["state"] for r in ctl.journal.records()]
        assert states[-2:] == [QUARANTINED, COOLDOWN]

    def test_fit_crash_retries_then_quarantines(self, champion,
                                                tmp_path):
        launcher = FakeLauncher(champion, behaviors=("crash",))
        ro = FakeRollout()
        pre = _dir_hashes(champion)
        ctl = _controller(champion, tmp_path / "r", launcher, ro,
                          fit_attempts=3)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            assert launcher.calls == 3  # bounded retries, then stop
            self._assert_contained(ctl, ro, champion, pre, "fit_failed")
            assert "fit_crash rc=13" in ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_fit_hang_killed_at_timeout(self, champion, tmp_path):
        launcher = FakeLauncher(champion, behaviors=("hang",))
        ro = FakeRollout()
        pre = _dir_hashes(champion)
        ctl = _controller(champion, tmp_path / "r", launcher, ro,
                          fit_timeout_s=0.3, fit_attempts=2)
        try:
            t0 = time.monotonic()
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            assert time.monotonic() - t0 < 5.0, "timeout not enforced"
            self._assert_contained(ctl, ro, champion, pre, "fit_failed")
            assert "fit_timeout" in ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_bad_artifact_fails_validation(self, champion, tmp_path):
        launcher = FakeLauncher(champion, behaviors=("bad_artifact",))
        ro = FakeRollout()
        pre = _dir_hashes(champion)
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            self._assert_contained(ctl, ro, champion, pre,
                                   "validation_failed")
            assert "unloadable" in ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_missing_monitor_profile_fails_validation(self, champion,
                                                      tmp_path):
        launcher = FakeLauncher(champion, behaviors=("no_monitor",))
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            assert "monitor.json" in ctl.quarantine_list()[0]["reason"]
            assert ro.start_calls == []
        finally:
            ctl.close()

    def test_low_holdout_metric_fails_validation(self, champion,
                                                 tmp_path):
        launcher = FakeLauncher(champion, behaviors=("low_metric",))
        ro = FakeRollout()
        pre = _dir_hashes(champion)
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            self._assert_contained(ctl, ro, champion, pre,
                                   "validation_failed")
            assert "outside tolerance" in \
                ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_rollout_rejection_quarantines(self, champion, tmp_path):
        launcher = FakeLauncher(champion)
        ro = FakeRollout(outcome="rejected")
        pre = _dir_hashes(champion)
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            # the rollout RAN (shadow) — rejection is its verdict; the
            # champion kept serving throughout
            assert len(ro.start_calls) == 1
            assert _dir_hashes(champion) == pre
            assert "rollout_rejected" in \
                ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_injected_rollout_reject_fault(self, champion, tmp_path,
                                           monkeypatch):
        monkeypatch.setenv(RF.FAULT_ENV, "rollout_reject")
        launcher = FakeLauncher(champion)
        ro = FakeRollout(outcome="swapped")
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            # the injected fault forces the rejected branch WITHOUT the
            # candidate ever reaching the real rollout path
            assert ro.start_calls == []
            assert "injected rollout_reject" in \
                ctl.quarantine_list()[0]["reason"]
        finally:
            ctl.close()

    def test_quarantined_candidate_never_retried_verbatim(
            self, champion, tmp_path):
        # cycle 1: clean fit, rollout rejects -> candidate hash in the
        # ledger. cycle 2 produces a byte-identical candidate -> it is
        # refused at VALIDATING, before any rollout.
        launcher = FakeLauncher(champion)
        ro = FakeRollout(outcome="rejected")
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="q1")
            n_start = len(ro.start_calls)
            ctl.trigger(force=True)
            _wait(lambda: ctl.quarantined_total == 2, msg="q2")
            assert len(ro.start_calls) == n_start  # no second rollout
            assert "byte-identical to a quarantined" in \
                ctl.quarantine_list()[1]["reason"]
        finally:
            ctl.close()

    def test_cooldown_suppresses_and_force_overrides(self, champion,
                                                     tmp_path):
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r", launcher, ro,
                          min_interval_s=60.0)
        try:
            ctl.trigger()
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            alert = {"window_id": "h:m:w1", "target": "a",
                     "metric": "js"}
            assert ctl.handle_alert(alert) == "cooldown"
            with pytest.raises(RetrainConflict):
                ctl.trigger()
            ctl.trigger(force=True)  # the operator override
            _wait(lambda: ctl.swapped_total == 2, msg="swap2")
        finally:
            ctl.close()

    def test_storm_breaker(self, champion, tmp_path):
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r", launcher, ro,
                          max_retrains_per_window=2,
                          storm_window_s=3600.0)
        try:
            for i in range(2):
                ctl.handle_alert({"window_id": f"h:m:w{i}",
                                  "target": "a", "metric": "js"})
                _wait(lambda: ctl.effective_state() == "idle",
                      msg="cycle")
            out = ctl.handle_alert({"window_id": "h:m:w9",
                                    "target": "a", "metric": "js"})
            assert out == "storm_breaker"
            assert ctl.cycles_total == 2
            with pytest.raises(RetrainConflict):
                ctl.trigger()  # un-forced manual respects the breaker
        finally:
            ctl.close()

    def test_cooldown_deferred_alert_retriggers_on_redelivery(
            self, champion, tmp_path):
        """An alert suppressed by a TRANSIENT condition (cooldown) is
        NOT consumed: the pooled /drift poll re-delivers the same
        window_id while the window stays open, and that re-delivery
        must trigger once the controller frees up — only a trigger (or
        a permanent suppression) consumes the dedupe key."""
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r", launcher, ro,
                          min_interval_s=0.5)
        alert = {"window_id": "h:m:w7", "target": "a", "metric": "js"}
        try:
            ctl.trigger()
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert ctl.handle_alert(dict(alert)) == "cooldown"
            _wait(lambda: ctl.effective_state() == "idle",
                  msg="cooldown decay")
            assert ctl.handle_alert(dict(alert)) is None  # NOT duplicate
            _wait(lambda: ctl.swapped_total == 2, msg="swap2")
            # consumed only once it actually acted
            assert ctl.handle_alert(dict(alert)) == "duplicate"
        finally:
            ctl.close()

    def test_graceful_close_mid_fitting_pauses_for_resume(
            self, champion, tmp_path):
        """close()/SIGTERM during FITTING must NOT quarantine: the
        journal keeps the cycle at FITTING and the next incarnation
        resumes it — an operator restart must never permanently ban a
        retrain (only kill -9 and real failures are exceptional)."""
        launcher = FakeLauncher(champion, behaviors=("crash", "ok"))
        ro = FakeRollout()
        root = tmp_path / "r"
        ctl = _controller(champion, root, launcher, ro,
                          backoff_base_s=30.0, backoff_cap_s=30.0,
                          fit_attempts=3)
        ctl.trigger()
        _wait(lambda: launcher.calls == 1, msg="first attempt")
        ctl.close()  # lands in the retry backoff -> pause, not fail
        assert ctl.quarantined_total == 0
        states = [r["state"] for r in ctl.journal.records()]
        assert QUARANTINED not in states and states[-1] == FITTING
        ctl2 = _controller(champion, root, FakeLauncher(champion), ro)
        try:
            out = ctl2.resume()
            assert out["resumed"] and out["at_state"] == FITTING
            _wait(lambda: ctl2.swapped_total == 1, msg="swap")
            assert ctl2.quarantined_total == 0
        finally:
            ctl2.close()

    def test_graceful_close_mid_rollout_pauses_for_resume(
            self, champion, tmp_path):
        """close() with the rollout still live leaves the rollout AND
        the journal's ROLLING_OUT record alone; the resumed controller
        finds the live rollout and awaits its verdict — exactly one
        rollout, no quarantine of a validated candidate."""
        class DistinctLauncher(FakeLauncher):
            # a real refit candidate is never byte-identical to the
            # champion; give it its own content hash so the resume
            # probe cannot mistake it for an already-landed swap
            def _write_candidate(self, spec, **kw):
                super()._write_candidate(spec, **kw)
                with open(os.path.join(spec.out_dir,
                                       "op-model.json"), "a") as fh:
                    fh.write("\n")
                rp = os.path.join(spec.out_dir, RF.REPORT_JSON)
                with open(rp) as fh:
                    rep = json.load(fh)
                rep["candidate_hash"] = model_content_hash(spec.out_dir)
                with open(rp, "w") as fh:
                    json.dump(rep, fh)

        ro = FakeRollout(outcome="swapped", delay=3600.0)  # stays live
        root = tmp_path / "r"
        ctl = _controller(champion, root, DistinctLauncher(champion), ro)
        ctl.trigger()
        _wait(lambda: ctl.state == ROLLING_OUT and ro.start_calls,
              msg="rolling out")
        ctl.close()
        assert ctl.quarantined_total == 0 and ro.aborted == 0
        states = [r["state"] for r in ctl.journal.records()]
        assert QUARANTINED not in states and states[-1] == ROLLING_OUT
        ctl2 = _controller(champion, root, DistinctLauncher(champion),
                           ro)
        try:
            out = ctl2.resume()
            assert out["resumed"]
            assert out["action"] == "awaiting_live_rollout"
            ro.set_delay(0.0)  # the verdict lands now
            _wait(lambda: ctl2.swapped_total == 1, msg="swap")
            assert len(ro.start_calls) == 1  # exactly one rollout
            assert ctl2.quarantined_total == 0
        finally:
            ctl2.close()

    def test_recipe_thresholds_passed_per_rollout(self, champion,
                                                  tmp_path):
        """The recipe's rollout_* relaxation rides start(thresholds=)
        for THAT cycle's rollout only — never a mutation of the shared
        manager (manual POST /rollout keeps the fleet's base guards);
        a recipe without the keys passes no kwarg at all (duck-typed
        fakes need not know it)."""
        ro = FakeRollout()
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro)
        ctl._recipe.update({"rollout_max_pred_js": 1.5,
                            "rollout_max_psi": 50.0})
        try:
            ctl.trigger()
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert ro.start_kwargs["thresholds"] == {
                "max_pred_js": 1.5, "max_psi": 50.0}
        finally:
            ctl.close()

    def test_rollout_verdict_threshold_overrides_scope(self):
        """RolloutManager: start(thresholds=) relaxes the verdict for
        one rollout; the next start resets to the base thresholds."""
        from transmogrifai_tpu.fleet.rollout import RolloutManager

        class _Router:
            champions = []

        class SeedableRollout(RolloutManager):
            """Test seam: seed the tallies the _shadow_loop thread
            would accumulate — under the manager's own lock, the same
            discipline _score_pair follows."""

            def seed_disjoint(self):
                with self.lock:
                    # fully disjoint score histograms: JS saturates
                    # at 1.0 (equal means keep the shift guard quiet)
                    self._v1_hist[0] = 50.0
                    self._v2_hist[-1] = 50.0
                    self.shadow_pairs = 50
                    self._v1_sum = self._v2_sum = 5.0

            def relax(self, **ov):
                with self.lock:
                    self._thresholds = ov

            def peek_thresholds(self):
                with self.lock:
                    return dict(self._thresholds)

        ro = SeedableRollout(object(), _Router(),
                             lock=threading.RLock())
        ro.seed_disjoint()
        assert not ro.verdict()["clean"]  # base guards reject
        ro.relax(max_pred_js=1.5, max_psi=50.0)
        assert ro.verdict()["clean"]  # this rollout's relaxation
        # a failed next start() (stub supervisor) still RESETS the
        # overrides before touching the pool — the relaxation never
        # leaks into a later operator rollout
        with pytest.raises(Exception):
            ro.start("/nope")
        assert ro.peek_thresholds() == {}
        ro.seed_disjoint()  # start() zeroed the shadow state
        assert not ro.verdict()["clean"]  # base guards are back

    def test_operator_abort_quarantines_without_banning(self, champion,
                                                        tmp_path):
        """An operator abort (RolloutManager.abort's `aborted` verdict
        marker) quarantines the cycle's evidence but does NOT ban the
        candidate hash or the trigger — the candidate didn't fail at
        traffic, someone needed the slot."""
        class AbortingRollout(FakeRollout):
            def start(self, *a, **kw):
                out = super().start(*a, **kw)
                self.abort()  # the operator wins the slot immediately
                return out

            def abort(self):
                with self._lk:
                    self.aborted += 1
                    self._state = "rejected"
                    self.last_verdict = {"clean": False,
                                         "reasons": ["aborted"],
                                         "aborted": True}

        ro = AbortingRollout()
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            q = ctl.quarantine_list()
            assert len(q) == 1 and "aborted" in q[0]["reason"], q
            assert q[0]["candidate_hash"] is None  # evidence, no ban
            assert q[0]["window_id"] is None
            assert not ctl._quarantined_hashes
            assert not ctl._quarantined_triggers
        finally:
            ctl.close()

    def test_graceful_close_racing_validation_does_not_ban(
            self, champion, tmp_path):
        """close() racing a long validation (the journal can close
        under the cycle thread after join(10) times out) must PAUSE the
        cycle for resume — an operator restart must never quarantine,
        let alone ban, a candidate that failed nothing."""
        ro = FakeRollout()
        entered = threading.Event()

        class RacingValidate(RetrainController):
            def _validate(self, cyc):
                entered.set()
                # a real monitor replay has no stop checks; model the
                # race by failing the way a closed-journal append
                # would, AFTER the stop landed
                _wait(lambda: self._stop.is_set(), msg="stop flag")
                raise ValueError("I/O operation on closed file")

        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro,
                          cls=RacingValidate)
        try:
            ctl.trigger()
            assert entered.wait(10.0)
            ctl.close()
            assert ctl.quarantined_total == 0
            assert ctl.swapped_total == 0
            assert not ctl._quarantined_hashes
            assert not ctl._quarantined_triggers
        finally:
            ctl.close()

    def test_resume_cooldown_counts_downtime(self, champion, tmp_path):
        """Restarting a day after the last cycle ended must NOT
        re-impose a full min_interval_s: resume() derives the cooldown
        from the journal's ts, so a genuine alert right after the
        restart triggers immediately."""
        root = tmp_path / "r"
        os.makedirs(root, exist_ok=True)
        with open(root / "journal.jsonl", "w") as fh:
            fh.write(json.dumps({"seq": 0, "ts": time.time() - 86400.0,
                                 "cycle": "rc-old",
                                 "state": COOLDOWN}) + "\n")
        ctl = _controller(champion, root, FakeLauncher(champion),
                          FakeRollout(), min_interval_s=3600.0)
        try:
            out = ctl.resume()
            assert out["reason"] == "last cycle complete"
            assert ctl._cooldown_remaining() <= 0.0
            assert ctl.effective_state() == "idle"
        finally:
            ctl.close()

    def test_foreign_rollout_verdict_not_booked(self, champion,
                                                tmp_path):
        """A terminal rollout state naming someone ELSE's challenger
        (ours died; an operator took the slot) must not be booked as
        this cycle's swap or rejection: the cycle ends quarantined
        without a verdict, without banning the candidate, and without
        aborting the foreign rollout."""
        class ForeignRollout(FakeRollout):
            def status(self):
                st = super().status()
                if st["state"] in ("swapped", "rejected"):
                    st["challenger_dir"] = "/someone/elses/v9"
                return st

        ro = ForeignRollout()  # flips terminal on first status() poll
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            assert ctl.swapped_total == 0
            q = ctl.quarantine_list()
            assert "did not reach a verdict" in q[0]["reason"], q
            assert q[0]["candidate_hash"] is None  # no ban either way
            assert not ctl._quarantined_hashes
            assert ro.aborted == 0  # never aborts a foreign rollout
        finally:
            ctl.close()

    def test_rollout_no_verdict_timeout_quarantines_without_ban(
            self, champion, tmp_path):
        """A rollout that never reaches a verdict inside the budget
        (thin shadow traffic) is aborted and quarantined — but the
        candidate is NOT banned: nothing about the artifact failed, so
        a later cycle may ship the same candidate."""
        ro = FakeRollout(delay=999.0)  # stuck in shadow forever
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro,
                          rollout_timeout_s=0.3)
        try:
            ctl.trigger()
            _wait(lambda: ctl.quarantined_total == 1, msg="quarantine")
            assert ro.aborted == 1  # ours: reclaim the slot
            q = ctl.quarantine_list()
            assert "did not reach a verdict" in q[0]["reason"], q
            assert q[0]["candidate_hash"] is None
            assert q[0]["window_id"] is None
            assert not ctl._quarantined_hashes
            assert not ctl._quarantined_triggers
        finally:
            ctl.close()

    def test_unconfigured_suppression_evented_once(self, champion,
                                                   tmp_path,
                                                   monkeypatch):
        """A recipe-less controller suppresses every re-delivered
        alert, but EVENTS the suppression once per episode — the
        pooled /drift poll re-delivers the alert fan-out every couple
        of seconds for as long as the recipe stays missing, and
        per-delivery events would flood the shared fleet log."""
        from transmogrifai_tpu.retrain import controller as rc
        # recipe=None AND the champion dir has no retrain.json
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout(),
                          recipe=None)
        evs = []
        monkeypatch.setattr(
            rc.collector, "event",
            lambda name, **kw: evs.append(name))
        try:
            for i in range(5):
                out = ctl.handle_alert({"window_id": f"h:m:w{i}",
                                        "target": "a", "metric": "js"})
                assert out == "unconfigured"
            assert ctl.suppressed["unconfigured"] == 5
            assert evs.count("retrain_suppressed") == 1
        finally:
            ctl.close()

    def test_rollout_conflict_retried_not_quarantined(self, champion,
                                                      tmp_path):
        """A transient RolloutConflict from rollout.start (another
        rollout holds the slot) waits for the slot instead of
        quarantining: quarantine would ban the validated candidate's
        hash forever over a momentary collision."""
        class RolloutConflict(RuntimeError):  # judged by NAME
            pass

        class BusyThenFree(FakeRollout):
            def __init__(self):
                super().__init__(outcome="swapped")
                self.conflicts = 2

            def start(self, *a, **kw):
                if self.conflicts > 0:
                    self.conflicts -= 1
                    raise RolloutConflict("slot busy")
                return super().start(*a, **kw)

        ro = BusyThenFree()
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro,
                          rollout_timeout_s=30.0)
        try:
            ctl.trigger()
            _wait(lambda: ctl.swapped_total == 1, timeout=30.0,
                  msg="swap after conflict retries")
            assert ro.conflicts == 0 and len(ro.start_calls) == 1
            assert ctl.quarantined_total == 0
        finally:
            ctl.close()

    def test_failed_journal_append_rolls_back_trigger(self, champion,
                                                      tmp_path):
        """A disk-full journal append during the trigger mint must roll
        the TRIGGERED reservation back to IDLE — not wedge the
        controller in a stateless TRIGGERED with no cycle thread
        (regression)."""
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout())
        try:
            real = ctl.journal.append
            fail_next = [True]

            def flaky(*a, **kw):
                if fail_next[0]:
                    fail_next[0] = False
                    raise OSError(28, "No space left on device")
                return real(*a, **kw)

            ctl.journal.append = flaky
            with pytest.raises(OSError):
                ctl.trigger(force=True)
            assert ctl.effective_state() == "idle"
            assert ctl.cycle is None and ctl.cycles_total == 0
            # and the controller is RETRIGGERABLE once the disk frees up
            ctl.trigger(force=True)
            _wait(lambda: ctl.swapped_total == 1, timeout=30.0,
                  msg="swap after journal recovery")
        finally:
            ctl.close()

    def test_failed_launch_leaves_alert_retriable(self, champion,
                                                  tmp_path):
        """A failed cycle mint must NOT consume the alert's dedupe key:
        the pooled poll's re-delivery of the same window is what retries
        the deferred trigger (regression)."""
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout())
        try:
            real = ctl.journal.append
            fail_next = [True]

            def flaky(*a, **kw):
                if fail_next[0]:
                    fail_next[0] = False
                    raise OSError(28, "No space left on device")
                return real(*a, **kw)

            ctl.journal.append = flaky
            alert = {"window_id": "h:m:w0", "target": "a",
                     "metric": "js"}
            with pytest.raises(OSError):
                ctl.handle_alert(alert)
            assert ctl.effective_state() == "idle"
            out = ctl.handle_alert(dict(alert))
            assert out is None, f"re-delivery suppressed as {out}"
            _wait(lambda: ctl.swapped_total == 1, timeout=30.0,
                  msg="swap after alert re-delivery")
        finally:
            ctl.close()

    def test_swap_landing_at_deadline_not_quarantined(self, champion,
                                                      tmp_path):
        """The shadow verdict can land in the race window between the
        timeout status read and abort()'s state guard (which no-ops on
        a terminal rollout). The cycle must book the swap — the old
        quarantine path would shutil.move cycles/<id>/ and relocate
        the SERVING champion's model dir out from under the fleet
        (regression)."""
        class SwapAtAbort(FakeRollout):
            def __init__(self):
                # never decides on its own: the controller times out
                super().__init__(outcome="swapped", delay=3600.0)

            def abort(self):
                with self._lk:
                    # simulate _decide winning the race: the real
                    # abort's state guard no-oped, the verdict is a
                    # REAL swap (no aborted marker)
                    self._state = "swapped"
                    self.last_verdict = {"clean": True, "reasons": []}

        ro = SwapAtAbort()
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), ro,
                          rollout_timeout_s=0.5)
        try:
            ctl.trigger(force=True)
            _wait(lambda: ctl.swapped_total == 1, timeout=30.0,
                  msg="swap booked after the abort race")
            assert ctl.quarantined_total == 0
            assert ctl.last_verdict["outcome"] == "swapped"
            # the cycle dir (holding the now-serving candidate) stayed
            cand = ctl.last_verdict["candidate_dir"]
            assert os.path.isdir(cand), "serving candidate dir moved!"
        finally:
            ctl.close()

    def test_status_not_blocked_by_cycle_mint(self, champion, tmp_path):
        """The heavy trigger mint (window CSV, spec, journal fsync)
        runs OUTSIDE the controller lock: /healthz (effective_state)
        must answer while the snapshot is in flight (regression)."""
        gate = threading.Event()
        entered = threading.Event()

        class SlowMint(RetrainController):
            def _snapshot_window(self, path):
                entered.set()
                gate.wait(10.0)
                return super()._snapshot_window(path)

        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout(),
                          cls=SlowMint)
        try:
            t = threading.Thread(
                target=lambda: ctl.trigger(force=True), daemon=True)
            t.start()
            assert entered.wait(5.0), "mint never reached the snapshot"
            t0 = time.monotonic()
            st = ctl.effective_state()
            elapsed = time.monotonic() - t0
            assert st == "triggered"
            assert elapsed < 1.0, f"state read blocked {elapsed:.1f}s " \
                                  f"behind the mint"
            gate.set()
            t.join(10.0)
            _wait(lambda: ctl.swapped_total == 1, timeout=30.0,
                  msg="swap after slow mint")
        finally:
            ctl.close()


# ---------------------------------------------------------------------------
# sandboxed artifact load probe: the child-process path the fake-driven
# suite bypasses with sandbox_load_probe=False
# ---------------------------------------------------------------------------

class TestSandboxedLoadProbe:
    def test_probe_contains_corruption_in_a_child(self, champion,
                                                  tmp_path):
        """Default-policy probe: a loadable artifact passes, a corrupt
        one is refused — and the refusal comes from a CHILD process
        (the serving process never deserializes the untrusted bytes)."""
        ctl = _controller(champion, tmp_path / "r",
                          FakeLauncher(champion), FakeRollout(),
                          sandbox_load_probe=True)
        ctl.env["JAX_PLATFORMS"] = "cpu"  # the child really starts jax
        try:
            assert ctl._load_probe(champion) is None
            bad = str(tmp_path / "bad")
            shutil.copytree(champion, bad)
            with open(os.path.join(bad, "op-model.json"), "w") as fh:
                fh.write("{corrupt")
            err = ctl._load_probe(bad)
            assert err, "corrupt artifact must be refused"
            assert "Error" in err  # the child named the exception
        finally:
            ctl.close()


# ---------------------------------------------------------------------------
# journal crash-resume: kill between each pair of adjacent states
# ---------------------------------------------------------------------------

class TestJournalResume:
    """Handcraft the journal a controller would have written up to each
    state, then construct a FRESH controller over the same root (the
    post-kill incarnation) and assert it resumes with EXACTLY one
    rollout and no duplicate work."""

    def _root(self, tmp_path, champion, journal_states,
              with_candidate=True, launcher=None):
        root = tmp_path / "r"
        os.makedirs(root, exist_ok=True)
        cyc_dir = str(root / "cycles" / "rc-test")
        cand_dir = os.path.join(cyc_dir, "candidate")
        os.makedirs(cyc_dir, exist_ok=True)
        RF.RefitSpec(champion_dir=champion, out_dir=cand_dir,
                     builder="nope:nope").save(
            os.path.join(cyc_dir, RF.SPEC_JSON))
        cand_hash = None
        if with_candidate:
            shutil.copytree(champion, cand_dir)
            # a real refit candidate is never byte-identical to the
            # champion; a trailing newline keeps the JSON valid while
            # giving the candidate its own content hash (the resume
            # probe compares hashes)
            with open(os.path.join(cand_dir, "op-model.json"), "a") as fh:
                fh.write("\n")
            cand_hash = model_content_hash(cand_dir)
            with open(os.path.join(cand_dir, RF.REPORT_JSON), "w") as fh:
                json.dump({"candidate_hash": cand_hash,
                           "metric": "au_pr",
                           "metric_larger_better": True,
                           "candidate_metric": 0.9,
                           "champion_metric": 0.9}, fh)
        j = RetrainJournal(str(root / "journal.jsonl"))
        for st in journal_states:
            fields = {}
            if st == TRIGGERED:
                fields = {"cycle_dir": cyc_dir, "champion_dir": champion,
                          "champion_hash": model_content_hash(champion),
                          "trigger": {"window_id": "h:m:w0"}}
            if st == FITTING:
                fields = {"attempt": 1}
            if st == ROLLING_OUT:
                fields = {"candidate_dir": cand_dir,
                          "candidate_hash": cand_hash}
            j.append("rc-test", st, **fields)
        j.close()
        return root, cand_dir, cand_hash

    def _resume(self, champion, root, launcher, ro,
                champion_dir_fn=None):
        return RetrainController(
            champion_dir_fn or (lambda: champion), root=str(root),
            rollout=ro,
            policy=RetrainPolicy(min_interval_s=0.0, fit_attempts=2,
                                 backoff_base_s=0.01,
                                 fit_timeout_s=5.0,
                                 require_monitor_green=False,
                                 rollout_timeout_s=10.0,
                                 sandbox_load_probe=False),
            recipe={"builder": "nope:nope", "history": []},
            launcher=launcher)

    def test_kill_after_triggered_resumes_through_fit(self, champion,
                                                      tmp_path):
        root, _, _ = self._root(tmp_path, champion, [TRIGGERED],
                                with_candidate=False)
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert out["resumed"] and out["at_state"] == TRIGGERED
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert launcher.calls == 1 and len(ro.start_calls) == 1
        finally:
            ctl.close()

    def test_kill_mid_fitting_relaunches_once(self, champion, tmp_path):
        root, _, _ = self._root(tmp_path, champion,
                                [TRIGGERED, FITTING],
                                with_candidate=False)
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert out["resumed"] and out["at_state"] == FITTING
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert launcher.calls == 1 and len(ro.start_calls) == 1
        finally:
            ctl.close()

    def test_kill_mid_validating_revalidates_once(self, champion,
                                                  tmp_path):
        root, _, _ = self._root(tmp_path, champion,
                                [TRIGGERED, FITTING, VALIDATING])
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert out["resumed"] and out["at_state"] == VALIDATING
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            # the candidate sat on disk across the kill: NO refit ran
            assert launcher.calls == 0
            assert len(ro.start_calls) == 1
        finally:
            ctl.close()

    def test_kill_mid_rollout_swap_already_landed(self, champion,
                                                  tmp_path):
        """The double-rollout hazard: the swap happened, THEN the
        controller died before journaling it. Resume must detect the
        landed swap (champion hash == candidate hash) and must NOT
        start a second rollout."""
        root, cand_dir, cand_hash = self._root(
            tmp_path, champion,
            [TRIGGERED, FITTING, VALIDATING, ROLLING_OUT])
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        # post-swap world: the candidate IS the serving champion now
        ctl = self._resume(champion, root, launcher, ro,
                           champion_dir_fn=lambda: cand_dir)
        try:
            out = ctl.resume()
            assert out["resumed"]
            assert out["action"] == "swap_already_landed"
            assert ro.start_calls == [], "second rollout started!"
            assert launcher.calls == 0
            _wait(lambda: ctl.swapped_total == 1, msg="bookkeeping")
            states = [r["state"] for r in ctl.journal.records()]
            assert states[-1] == COOLDOWN
        finally:
            ctl.close()

    def test_swap_already_landed_credits_restart_downtime(self, champion,
                                                          tmp_path):
        """The cycle actually ENDED (swap landed) before the crash:
        restart downtime counts toward the cooldown on this resume
        branch too, like COOLDOWN/QUARANTINED (regression)."""
        root, cand_dir, _ = self._root(
            tmp_path, champion,
            [TRIGGERED, FITTING, VALIDATING, ROLLING_OUT])
        # age the journal: the crash (and the landed swap) was 1000s ago
        jp = os.path.join(str(root), "journal.jsonl")
        with open(jp) as fh:
            recs = [json.loads(ln) for ln in fh if ln.strip()]
        for r in recs:
            r["ts"] = float(r["ts"]) - 1000.0
        with open(jp, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        ctl = RetrainController(
            lambda: cand_dir, root=str(root), rollout=FakeRollout(),
            policy=RetrainPolicy(min_interval_s=600.0,
                                 require_monitor_green=False,
                                 sandbox_load_probe=False),
            recipe={"builder": "nope:nope", "history": []},
            launcher=FakeLauncher(champion))
        try:
            out = ctl.resume()
            assert out["action"] == "swap_already_landed"
            assert ctl.swapped_total == 1
            # 1000s of downtime > the 600s min_interval: no residual
            # cooldown may block a real alert arriving after restart
            assert ctl.effective_state() == "idle"
        finally:
            ctl.close()

    def test_kill_mid_rollout_not_landed_recovers_one_rollout(
            self, champion, tmp_path):
        """The rollout died WITH the controller (challenger pool gone,
        no swap): resume re-validates and runs exactly one recovery
        rollout."""
        root, _, _ = self._root(
            tmp_path, champion,
            [TRIGGERED, FITTING, VALIDATING, ROLLING_OUT])
        launcher = FakeLauncher(champion)
        ro = FakeRollout()  # idle: the pre-kill rollout left no trace
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert out["resumed"] and "re-enter" in out["action"]
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            assert len(ro.start_calls) == 1
            assert launcher.calls == 0  # candidate reused, not refit
        finally:
            ctl.close()

    def test_kill_between_quarantined_and_cooldown(self, champion,
                                                   tmp_path):
        root, _, _ = self._root(
            tmp_path, champion,
            [TRIGGERED, FITTING, QUARANTINED])
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert not out["resumed"]  # terminal cycle: only bookkeeping
            assert launcher.calls == 0 and ro.start_calls == []
            states = [r["state"] for r in ctl.journal.records()]
            assert states[-1] == COOLDOWN
        finally:
            ctl.close()

    def test_clean_journal_resume_is_noop(self, champion, tmp_path):
        root, _, _ = self._root(
            tmp_path, champion, [TRIGGERED, FITTING, VALIDATING,
                                 ROLLING_OUT, COOLDOWN])
        launcher = FakeLauncher(champion)
        ro = FakeRollout()
        ctl = self._resume(champion, root, launcher, ro)
        try:
            out = ctl.resume()
            assert not out["resumed"]
            assert launcher.calls == 0 and ro.start_calls == []
        finally:
            ctl.close()

    def test_orphan_pid_reuse_guard(self, champion, tmp_path):
        """A pid file pointing at a process that is NOT a
        retrain-worker (pid reuse after reboot) must be left alone."""
        import subprocess
        import sys
        root, _, _ = self._root(tmp_path, champion,
                                [TRIGGERED, FITTING],
                                with_candidate=False)
        bystander = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(30)"])
        try:
            cyc_dir = str(root / "cycles" / "rc-test")
            with open(os.path.join(cyc_dir, "worker.pid"), "w") as fh:
                fh.write(str(bystander.pid))
            launcher = FakeLauncher(champion)
            ctl = self._resume(champion, root, launcher, FakeRollout())
            try:
                ctl.resume()
                _wait(lambda: ctl.swapped_total == 1, msg="swap")
                assert bystander.poll() is None, \
                    "resume killed an innocent bystander process"
            finally:
                ctl.close()
        finally:
            bystander.kill()
            bystander.wait(10)


# ---------------------------------------------------------------------------
# across-time GLM warm seed (ops/glm_sweep warm_seed)
# ---------------------------------------------------------------------------

class TestWarmSeed:
    def _problem(self, n=400, d=6, F=2, G=2, seed=0):
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        beta_true = rng.normal(size=d).astype(np.float32)
        y = (X @ beta_true + 0.1 * rng.normal(size=n) > 0
             ).astype(np.float32)
        w = np.ones(n, np.float32)
        masks = np.ones((F, n), np.float32)
        regs = np.asarray([0.1, 0.01], np.float32)[:G]
        alphas = np.zeros(G, np.float32)
        return (jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
                jnp.asarray(masks), regs, alphas)

    def test_warm_seed_matches_cold_and_converges_faster(self):
        from transmogrifai_tpu.ops import glm_sweep as GS
        X, y, w, m, regs, alphas = self._problem()
        kw = dict(loss="logistic", max_iter=60, tol=1e-6,
                  fit_intercept=True, standardize=True)
        B_cold, b0_cold, info_cold = GS.sweep_glm_streamed_rounds(
            X, y, w, m, regs, alphas, **kw)
        assert not info_cold["warm_seeded"]
        # seed from the cold solution of fold 0, lowest reg (a stand-in
        # for "the serving champion's coefficients")
        seed = (np.asarray(B_cold[0, -1]), float(b0_cold[0, -1]))
        B_warm, b0_warm, info_warm = GS.sweep_glm_streamed_rounds(
            X, y, w, m, regs, alphas, warm_seed=seed, **kw)
        assert info_warm["warm_seeded"]
        assert info_warm["warm_start"]  # the seed replaces round 0
        np.testing.assert_allclose(B_warm, B_cold, atol=5e-3)
        np.testing.assert_allclose(b0_warm, b0_cold, atol=5e-3)
        # starting at (essentially) the optimum costs fewer data passes
        assert info_warm["data_passes"] <= info_cold["data_passes"]

    def test_warm_seed_dimension_mismatch_is_ignored(self):
        from transmogrifai_tpu.ops import glm_sweep as GS
        X, y, w, m, regs, alphas = self._problem(d=6)
        bad_seed = (np.zeros(9, np.float32), 0.0)
        B, b0, info = GS.sweep_glm_streamed_rounds(
            X, y, w, m, regs, alphas, loss="logistic", max_iter=20,
            tol=1e-5, warm_seed=bad_seed)
        assert not info["warm_seeded"]  # cold start, not a crash

    def test_champion_shortcuts_applied_to_selector(self, champion):
        from transmogrifai_tpu.retrain.refit import (
            apply_champion_shortcuts, champion_config)
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        model = WorkflowModel.load(champion)
        cfg = champion_config(model)
        assert cfg["best_model_name"] == "OpLogisticRegression"
        assert cfg["coef"] is not None and cfg["coef"].ndim == 1
        # a fresh 2-model workflow narrows to the champion's winner
        from transmogrifai_tpu import FeatureBuilder
        from transmogrifai_tpu.automl import \
            BinaryClassificationModelSelector
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        from transmogrifai_tpu.models.glm import (OpLinearSVC,
                                                  OpLogisticRegression)
        from transmogrifai_tpu.stages.params import param_grid
        from transmogrifai_tpu.workflow import Workflow
        fa = FeatureBuilder.Real("a").extract(
            lambda r: r.get("a")).as_predictor()
        fy = FeatureBuilder.RealNN("y").extract(
            lambda r: r.get("y")).as_response()
        pred = BinaryClassificationModelSelector \
            .with_train_validation_split(
                models_and_parameters=[
                    (OpLogisticRegression(),
                     param_grid(reg_param=[0.01, 0.1])),
                    (OpLinearSVC(), param_grid(reg_param=[0.01]))],
            ).set_input(fy, transmogrify([fa])).get_output()
        wf = Workflow().set_result_features(pred)
        applied = apply_champion_shortcuts(wf, cfg, narrow=True,
                                           warm=True)
        assert applied == {"narrowed": True, "warm_seeded": True}
        sel = pred.origin_stage
        assert len(sel.models) == 1
        assert type(sel.models[0][0]).__name__ == "OpLogisticRegression"
        assert sel.models[0][1] == [cfg["best_grid"]]
        assert sel.warm_seed is not None


# ---------------------------------------------------------------------------
# the refit worker (in-process; the subprocess path is ci.sh's smoke)
# ---------------------------------------------------------------------------

BUILDER_SRC = '''
from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.workflow import Workflow


def build():
    fa = FeatureBuilder.Real("a").extract(
        lambda r: r.get("a")).as_predictor()
    fb = FeatureBuilder.Real("b").extract(
        lambda r: r.get("b")).as_predictor()
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(max_iter=10),
                                param_grid(reg_param=[0.01, 0.1]))],
    ).set_input(fy, transmogrify([fa, fb])).get_output()
    return Workflow().set_result_features(pred)
'''


class TestRefitWorker:
    def _spec(self, champion, tmp_path, **kw):
        import csv
        bdir = tmp_path / "builders"
        bdir.mkdir(exist_ok=True)
        with open(bdir / "retrain_builder_t.py", "w") as fh:
            fh.write(BUILDER_SRC)
        hist = tmp_path / "history.csv"
        with open(hist, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=["a", "b", "y"])
            w.writeheader()
            for r in _make_rows(240, seed=3):
                w.writerow(r)
        args = dict(champion_dir=champion,
                    out_dir=str(tmp_path / "candidate"),
                    builder="retrain_builder_t:build",
                    builder_path=str(bdir),
                    history=[str(hist)], holdout_fraction=0.25, seed=5)
        args.update(kw)
        return RF.RefitSpec(**args)

    def test_refit_produces_candidate_and_report(self, champion,
                                                 tmp_path):
        spec = self._spec(champion, tmp_path)
        report = RF.run_refit(spec)
        assert os.path.exists(os.path.join(spec.out_dir,
                                           "op-model.json"))
        assert os.path.exists(os.path.join(spec.out_dir, "monitor.json"))
        assert report["metric"] == "au_pr"
        assert report["candidate_metric"] is not None
        assert report["champion_metric"] is not None
        assert report["narrowed"] and report["warm_seeded"]
        assert report["candidate_hash"] == \
            model_content_hash(spec.out_dir)
        assert report["holdout_rows"] == 60
        # candidate must actually LOAD + score
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        m = WorkflowModel.load(spec.out_dir)
        assert m.score_function()({"a": 0.2, "b": -0.1})

    def test_warm_seeded_reported_false_on_dimension_mismatch(
            self, champion, tmp_path):
        """The report's warm_seeded is what the fit DID, not what was
        assigned: a builder whose vectorization changed dimension (here
        feature `b` dropped) forces the documented honest cold start,
        and the report must not claim the across-time warm start."""
        bdir = tmp_path / "builders"
        bdir.mkdir(exist_ok=True)
        with open(bdir / "retrain_builder_1f.py", "w") as fh:
            fh.write(BUILDER_SRC.replace(
                "transmogrify([fa, fb])", "transmogrify([fa])"))
        spec = self._spec(champion, tmp_path,
                          builder="retrain_builder_1f:build")
        report = RF.run_refit(spec)
        assert report["warm_seeded"] is False
        assert report["narrowed"]  # the other shortcut still applied

    def test_refit_copies_recipe_into_candidate(self, champion,
                                                tmp_path):
        """The candidate inherits the champion's retrain.json: after a
        swap it IS the champion dir, and the next cycle (or a fleet
        started fresh on it) must find the recipe there — continuous
        retraining, not one-shot."""
        champ2 = str(tmp_path / "champ2")
        shutil.copytree(champion, champ2)
        with open(os.path.join(champ2, RF.RECIPE_JSON), "w") as fh:
            json.dump({"builder": "retrain_builder_t:build",
                       "history": []}, fh)
        spec = self._spec(champ2, tmp_path)
        RF.run_refit(spec)
        assert RF.load_recipe(spec.out_dir) is not None

    def test_labeled_window_rows_join_training(self, champion,
                                               tmp_path):
        import csv
        win = tmp_path / "window.csv"
        with open(win, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=["a", "b", "y"])
            w.writeheader()
            rows = _make_rows(40, seed=9)
            for i, r in enumerate(rows):
                if i % 2:
                    r = {"a": r["a"], "b": r["b"], "y": ""}  # unlabeled
                w.writerow(r)
        spec = self._spec(champion, tmp_path, window=str(win))
        report = RF.run_refit(spec)
        assert report["window_rows"] == 40
        assert report["window_rows_labeled"] == 20
        assert report["train_rows"] + report["holdout_rows"] == 260

    def test_validation_fail_fault_reports_failing_metric(
            self, champion, tmp_path, monkeypatch):
        monkeypatch.setenv(RF.FAULT_ENV, "validation_fail")
        spec = self._spec(champion, tmp_path)
        report = RF.run_refit(spec)
        assert report["fault_injected"] == "validation_fail"
        assert report["candidate_metric"] == 0.0

    def test_bad_artifact_fault_corrupts_candidate(self, champion,
                                                   tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv(RF.FAULT_ENV, "bad_artifact")
        spec = self._spec(champion, tmp_path)
        RF.run_refit(spec)
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        with pytest.raises(Exception):
            WorkflowModel.load(spec.out_dir)


# ---------------------------------------------------------------------------
# fleet HTTP surface: POST /retrain + GET /retrainz
# ---------------------------------------------------------------------------

class TestFleetEndpoints:
    def _frontend(self, champion, tmp_path, launcher, ro):
        import threading as th

        from transmogrifai_tpu.fleet.frontend import (FleetFrontend,
                                                      make_fleet_server)
        from transmogrifai_tpu.fleet.router import Router
        ctl = _controller(champion, tmp_path / "r", launcher, ro)
        router = Router(th.RLock())
        fe = FleetFrontend(None, router, None, retrain=ctl)
        httpd = make_fleet_server(fe)
        t = th.Thread(target=httpd.serve_forever,
                      kwargs={"poll_interval": 0.05}, daemon=True)
        t.start()
        host, port = httpd.server_address[:2]
        return ctl, fe, httpd, host, port

    def test_retrain_endpoints(self, champion, tmp_path):
        from transmogrifai_tpu.fleet.router import http_json
        launcher = FakeLauncher(champion)
        launcher.block = threading.Event()
        ro = FakeRollout()
        ctl, fe, httpd, host, port = self._frontend(
            champion, tmp_path, launcher, ro)
        try:
            st, data = http_json(host, port, "GET", "/retrainz")
            assert st == 200
            assert json.loads(data)["state"] == "idle"
            st, data = http_json(host, port, "POST", "/retrain",
                                 body=b"{}")
            assert st == 200
            # concurrent trigger -> 409, mirroring RolloutConflict
            st, data = http_json(host, port, "POST", "/retrain",
                                 body=b"{}")
            assert st == 409, data
            assert "already" in json.loads(data)["error"]
            st, data = http_json(host, port, "GET", "/retrainz")
            payload = json.loads(data)
            assert payload["state"] in ("triggered", "fitting")
            assert payload["cycle"] is not None
            launcher.block.set()
            _wait(lambda: ctl.swapped_total == 1, msg="swap")
            st, data = http_json(host, port, "GET", "/retrainz")
            payload = json.loads(data)
            assert payload["swapped_total"] == 1
            assert payload["quarantine"] == []
        finally:
            launcher.block.set()
            httpd.shutdown()
            httpd.server_close()
            fe.close()
            ctl.close()

    def test_retrainz_404_when_unconfigured(self):
        import threading as th

        from transmogrifai_tpu.fleet.frontend import (FleetFrontend,
                                                      make_fleet_server)
        from transmogrifai_tpu.fleet.router import Router, http_json
        fe = FleetFrontend(None, Router(th.RLock()), None)
        httpd = make_fleet_server(fe)
        t = th.Thread(target=httpd.serve_forever,
                      kwargs={"poll_interval": 0.05}, daemon=True)
        t.start()
        host, port = httpd.server_address[:2]
        try:
            st, _ = http_json(host, port, "GET", "/retrainz")
            assert st == 404
            st, _ = http_json(host, port, "POST", "/retrain", body=b"{}")
            assert st == 404
        finally:
            httpd.shutdown()
            httpd.server_close()
            fe.close()
