"""ModelInsights + RecordInsightsLOCO.

Mirrors reference suites core/src/test/.../ModelInsightsTest.scala and
.../impl/insights/RecordInsightsLOCOTest.scala.
"""
import json

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.insights import (
    RecordInsightsLOCO, extract_insights, model_contributions)
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.workflow import Workflow


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(11)
    rows = []
    for _ in range(500):
        strong = float(rng.normal())
        weak = float(rng.normal())
        noise = float(rng.normal())
        label = float(2.5 * strong + 0.3 * weak + rng.normal(0, 0.5) > 0)
        rows.append({"strong": strong, "weak": weak, "noise": noise,
                     "label": label})
    fs = FeatureBuilder.Real("strong").extract(
        lambda r: r.get("strong")).as_predictor()
    fw = FeatureBuilder.Real("weak").extract(
        lambda r: r.get("weak")).as_predictor()
    fn = FeatureBuilder.Real("noise").extract(
        lambda r: r.get("noise")).as_predictor()
    fy = FeatureBuilder.RealNN("label").extract(
        lambda r: r.get("label")).as_response()
    vec = transmogrify([fs, fw, fn])
    checked = SanityChecker().set_input(fy, vec).get_output()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[
            (OpLogisticRegression(), param_grid(reg_param=[0.01]))],
    ).set_input(fy, checked).get_output()
    wf = Workflow().set_reader(ListReader(rows)).set_result_features(pred)
    return wf.train(), rows


class TestModelInsights:
    def test_contributions_rank_strong_first(self, fitted):
        model, _ = fitted
        mi = model.model_insights()
        by_name = {f.feature_name: f for f in mi.features}
        assert by_name["strong"].max_contribution() > \
            by_name["weak"].max_contribution()
        assert by_name["strong"].max_contribution() > \
            by_name["noise"].max_contribution()

    def test_correlations_populated(self, fitted):
        model, _ = fitted
        mi = model.model_insights()
        by_name = {f.feature_name: f for f in mi.features}
        assert by_name["strong"].max_corr() > 0.5
        assert by_name["strong"].max_corr() > by_name["noise"].max_corr()

    def test_selected_model_and_evals(self, fitted):
        model, _ = fitted
        mi = model.model_insights()
        assert mi.selected_model["best_model_type"] == "OpLogisticRegression"
        assert mi.problem_type == "binary"
        assert "au_pr" in mi.train_evaluation
        assert mi.label_name == "label"

    def test_json_serializable(self, fitted):
        model, _ = fitted
        j = model.model_insights().to_json()
        assert json.dumps(j)  # round-trips through JSON

    def test_pretty_tables(self, fitted):
        model, _ = fitted
        s = model.model_insights().pretty()
        assert "Top Model Contributions" in s
        assert "Top Correlations" in s
        assert "strong" in s

    def test_tree_contributions(self):
        X = np.random.default_rng(5).normal(size=(400, 4)).astype(np.float32)
        y = ((X[:, 1] > 0)).astype(np.float32)
        m = OpGBTClassifier(max_iter=10, max_depth=3).fit_arrays(X, y)
        imp = model_contributions(m, 4)
        assert imp is not None and imp.argmax() == 1
        assert imp.sum() == pytest.approx(1.0, abs=1e-6)


class TestLOCO:
    def test_loco_ranks_causal_column(self):
        rng = np.random.default_rng(9)
        X = rng.normal(size=(50, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        model = OpLogisticRegression(reg_param=0.01).fit_arrays(X, y)
        loco = RecordInsightsLOCO(model=model, top_k=2)
        deltas = loco.insights_matrix(X)
        assert deltas.shape == (50, 3, 2)
        # column 0 must dominate the attribution for nearly every row
        strongest = np.abs(deltas).max(axis=2).argmax(axis=1)
        assert (strongest == 0).mean() > 0.9

    def test_loco_transform_emits_topk_maps(self, fitted):
        model, rows = fitted
        sel = model._selected_model()
        sc = model._sanity_checker()
        scored = model.transform()
        vec_col = scored.column(sc.output_name())
        loco = RecordInsightsLOCO(model=sel, top_k=2)
        out = loco.transform_columns(vec_col)
        first = out.data[0]
        assert isinstance(first, dict) and len(first) == 2
        for k, v in first.items():
            deltas = json.loads(v)
            assert isinstance(k, str) and len(deltas) == 2  # two classes

    def test_loco_zero_for_constant_column(self):
        rng = np.random.default_rng(10)
        X = rng.normal(size=(30, 3)).astype(np.float32)
        X[:, 2] = 0.0
        y = (X[:, 0] > 0).astype(np.float32)
        model = OpLogisticRegression().fit_arrays(X, y)
        loco = RecordInsightsLOCO(model=model)
        deltas = loco.insights_matrix(X)
        assert np.abs(deltas[:, 2, :]).max() < 1e-6


class TestModelInsightsDepth:
    """VERDICT r3 #9: per-derived-column records at reference depth
    (Insights/LabelSummary fields of ModelInsights.scala:280-390)."""

    def test_label_summary(self, fitted):
        model, _ = fitted
        lab = model.model_insights().label
        assert lab.label_name == "label"
        assert lab.raw_feature_name == ["label"]
        assert lab.raw_feature_type == ["RealNN"]
        assert lab.sample_size == 500
        assert lab.distribution["kind"] == "discrete"
        assert sorted(lab.distribution["domain"]) == ["0.0", "1.0"]
        assert sum(lab.distribution["prob"]) == pytest.approx(1.0)

    def test_derived_columns_carry_stats_and_stages(self, fitted):
        model, _ = fitted
        mi = model.model_insights()
        by_name = {f.feature_name: f for f in mi.features}
        d = by_name["strong"].derived[0]
        assert d.mean is not None and d.variance is not None
        assert d.min is not None and d.max is not None
        assert d.excluded is False
        assert d.contributions, "per-class contributions missing"
        assert any("vecReal" in s or "sanityCheck" in s or s
                   for s in d.stages_applied)

    def test_stage_info_map(self, fitted):
        model, _ = fitted
        mi = model.model_insights()
        assert mi.stage_info, "stage_info empty"
        assert any("sanityCheck" in k for k in mi.stage_info)

    def test_titanic_insights_list_every_raw_feature(self):
        """Reference-flow acceptance: insights JSON for the titanic example
        lists every raw predictor with derived columns + checker stats +
        model contribution (VERDICT r3 #9 'done' bar)."""
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "examples"))
        import op_titanic_simple as t
        wf, _ = t.build_workflow()
        model = wf.set_reader(
            ListReader(t.synthetic_passengers(400))).train()
        mi = model.model_insights()
        by_name = {f.feature_name: f for f in mi.features}
        for raw in ("pClass", "age", "sibSp", "parCh", "embarked"):
            assert raw in by_name, f"{raw} missing from insights"
            fi = by_name[raw]
            assert fi.derived, f"{raw} has no derived columns"
            kept = [d for d in fi.derived if d.column_index >= 0]
            assert any(d.contribution is not None for d in kept) or \
                fi.excluded_by, raw
            assert any(d.mean is not None for d in kept) or fi.excluded_by
            assert all(d.stages_applied for d in kept), raw
        # one-hot pivot columns carry categorical group stats
        cat_cols = [d for f in mi.features for d in f.derived
                    if d.indicator_value is not None
                    and d.column_index >= 0]
        assert any(d.count_matrix for d in cat_cols), \
            "no contingency stats on categorical columns"
        assert any(d.mutual_information is not None for d in cat_cols)
        j = json.dumps(mi.to_json())
        assert "count_matrix" in j and "stages_applied" in j
