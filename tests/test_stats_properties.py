"""Property tests: ops/stats correlation & contingency kernels vs scipy.

Reference analogue: utils/src/test/.../stats/OpStatisticsTest.scala and
SanityCheckerTest correlation assertions (Spark MLlib Statistics as the
oracle; scipy here).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.stats

from transmogrifai_tpu.ops import stats as S


@pytest.mark.parametrize("seed", range(5))
def test_pearson_with_label_matches_scipy(seed):
    rng = np.random.default_rng(seed)
    n, d = 300, 4
    X = rng.normal(size=(n, d))
    y = X @ rng.normal(size=d) + rng.normal(size=n)
    got = np.asarray(S.pearson_with_label(jnp.asarray(X, jnp.float32),
                                          jnp.asarray(y, jnp.float32)))
    for j in range(d):
        want = scipy.stats.pearsonr(X[:, j], y).statistic
        assert abs(got[j] - want) < 1e-4, (j, got[j], want)


@pytest.mark.parametrize("seed", range(5))
def test_spearman_with_label_matches_scipy_on_ties(seed):
    rng = np.random.default_rng(seed)
    n = 250
    # heavily tied discrete columns — the post-pivot case VERDICT r1 flagged
    X = np.stack([rng.integers(0, 4, size=n).astype(float),
                  np.round(rng.normal(size=n), 1)], axis=1)
    y = X[:, 0] * 2 + rng.normal(size=n)
    got = np.asarray(S.spearman_with_label(jnp.asarray(X, jnp.float32),
                                           jnp.asarray(y, jnp.float32)))
    for j in range(2):
        want = scipy.stats.spearmanr(X[:, j], y).statistic
        assert abs(got[j] - want) < 1e-3, (j, got[j], want)


@pytest.mark.parametrize("seed", range(4))
def test_chi2_cramers_v_match_scipy(seed):
    rng = np.random.default_rng(seed)
    table = rng.integers(1, 60, size=(3, 4)).astype(np.float64)
    got = S.contingency_stats(jnp.asarray(table, jnp.float32))
    chi2, _, _, _ = scipy.stats.chi2_contingency(table, correction=False)
    assert abs(float(got.chi2) - chi2) / max(chi2, 1.0) < 1e-3
    n = table.sum()
    k = min(table.shape) - 1
    cramers = np.sqrt(chi2 / (n * k))
    assert abs(float(got.cramers_v) - cramers) < 1e-3


def test_col_stats_weighted():
    rng = np.random.default_rng(7)
    x = rng.normal(size=200)
    x[::13] = np.nan
    w = rng.choice([0.5, 1.0, 2.0], size=200)
    st = S.col_stats(jnp.asarray(x[:, None], jnp.float32), jnp.asarray(w))
    ok = ~np.isnan(x)
    wsum = w[ok].sum()
    mean = (w[ok] * x[ok]).sum() / wsum
    # unbiased weighted variance (Spark colStats convention: /(count-1))
    var = (w[ok] * (x[ok] - mean) ** 2).sum() / (wsum - 1.0)
    assert abs(float(np.asarray(st.mean)[0]) - mean) < 1e-4
    assert abs(float(np.asarray(st.variance)[0]) - var) < 2e-3
    assert abs(float(np.asarray(st.min)[0]) - np.nanmin(x)) < 1e-6
    assert abs(float(np.asarray(st.max)[0]) - np.nanmax(x)) < 1e-6


@pytest.mark.parametrize("seed", range(3))
def test_histogram_batched_matches_numpy(seed):
    """The batched all-columns histogram (RawFeatureFilter's numeric fill
    path) vs np.histogram per column; NaN mass lands in the last bin."""
    rng = np.random.default_rng(seed)
    n, K, bins = 500, 4, 16
    V = rng.normal(size=(n, K))
    V[rng.uniform(size=(n, K)) < 0.1] = np.nan
    lo = np.nanmin(V, axis=0)
    hi = np.nanmax(V, axis=0)
    got = np.asarray(S.histogram_batched(
        jnp.asarray(V, jnp.float32), jnp.asarray(lo, jnp.float32),
        jnp.asarray(hi, jnp.float32), bins))
    assert got.shape == (K, bins + 1)
    for k in range(K):
        ok = np.isfinite(V[:, k])
        assert got[k, bins] == (~ok).sum()          # missing bin
        assert got[k, :bins].sum() == ok.sum()      # mass conservation
        # interior bins match numpy's fixed-range histogram; the engine
        # clips the top edge INTO the last bin like np.histogram does
        want, _ = np.histogram(V[ok, k], bins=bins,
                               range=(float(lo[k]), float(hi[k])))
        # f32 binning can shift boundary-straddling values by one bin
        assert np.abs(got[k, :bins] - want).sum() <= 2


def test_contingency_stats_host_vs_scipy():
    rng = np.random.default_rng(11)
    table = rng.integers(1, 80, size=(4, 3)).astype(np.float64)
    got = S.contingency_stats_host(table)
    chi2, _, _, _ = scipy.stats.chi2_contingency(table, correction=False)
    assert abs(got.chi2 - chi2) / max(chi2, 1.0) < 1e-9
    k = min(table.shape) - 1
    assert abs(got.cramers_v
               - np.sqrt(chi2 / (table.sum() * k))) < 1e-9
    # rule confidence/support definitions
    np.testing.assert_allclose(got.max_rule_confidences,
                               (table / table.sum(1, keepdims=True)).max(1))
    np.testing.assert_allclose(got.supports,
                               table.sum(1) / table.sum())


def test_js_divergence_properties():
    rng = np.random.default_rng(9)
    p = rng.dirichlet(np.ones(16))
    q = rng.dirichlet(np.ones(16))
    jsd_pq = float(S.js_divergence(jnp.asarray(p, jnp.float32),
                                   jnp.asarray(q, jnp.float32)))
    jsd_qp = float(S.js_divergence(jnp.asarray(q, jnp.float32),
                                   jnp.asarray(p, jnp.float32)))
    assert abs(jsd_pq - jsd_qp) < 1e-5            # symmetric
    assert 0.0 <= jsd_pq <= 1.0 + 1e-6            # bounded (bits — log2,
    # the reference FeatureDistribution.jsDivergence convention)
    self_d = float(S.js_divergence(jnp.asarray(p, jnp.float32),
                                   jnp.asarray(p, jnp.float32)))
    assert abs(self_d) < 1e-6                     # identity
    m = (p + q) / 2
    kl = lambda a, b: float((a * np.log2(a / b)).sum())
    want = 0.5 * kl(p, m) + 0.5 * kl(q, m)
    assert abs(jsd_pq - want) < 1e-4


@pytest.mark.parametrize("seed", range(3))
def test_pearson_with_label_pairwise_complete_on_nans(seed):
    """NaN entries drop out per column (pairwise-complete), matching scipy
    on the complete pairs (VERDICT r1 statistical-parity item)."""
    rng = np.random.default_rng(seed)
    n = 400
    X = rng.normal(size=(n, 3))
    X[rng.uniform(size=(n, 3)) < 0.2] = np.nan
    y = np.nansum(X, axis=1) + rng.normal(size=n)
    got = np.asarray(S.pearson_with_label(jnp.asarray(X, jnp.float32),
                                          jnp.asarray(y, jnp.float32)))
    for j in range(3):
        ok = np.isfinite(X[:, j])
        want = scipy.stats.pearsonr(X[ok, j], y[ok]).statistic
        assert abs(got[j] - want) < 1e-3, (j, got[j], want)
