"""Batched LOCO knockout routes vs the generic host loop (parity oracle).

VERDICT r3 #10: the knockout axis must be a device program, not D host
passes. Every supported family's batched route (insights/knockout.py) must
reproduce the loop's [n, d, c] delta tensor bitwise-closely; unknown models
must still fall back to the loop.
"""
import numpy as np
import pytest

from transmogrifai_tpu.insights.loco import RecordInsightsLOCO
from transmogrifai_tpu.insights.knockout import knockout_deltas
from transmogrifai_tpu.models.glm import (
    OpLinearRegression, OpLinearSVC, OpLogisticRegression, OpNaiveBayes)
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier, OpGBTRegressor, OpRandomForestClassifier,
    OpRandomForestRegressor, OpXGBoostClassifier, OpXGBoostRegressor)


def _data(seed=0, n=80, d=6, classes=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    if classes == 1:   # regression
        y = (X[:, 0] * 2 - X[:, 2] + rng.normal(size=n) * 0.1).astype(
            np.float32)
    else:
        y = (np.argsort(X[:, 0] + 0.5 * X[:, 1])
             * classes // n).astype(np.float32)
    return X, y


def _assert_parity(model, X, tol=1e-6, tree=False):
    loco = RecordInsightsLOCO(model=model)
    # force_tree exercises the scan route even on a CPU backend, where the
    # dispatcher prefers the host loop's native traversal
    batched = knockout_deltas(model, X, force_tree=True if tree else None)
    assert batched is not None, f"no batched route for {type(model).__name__}"
    loop = loco.insights_matrix_loop(X)
    assert batched.shape == loop.shape
    np.testing.assert_allclose(batched, loop, atol=tol, rtol=1e-4)
    if not tree:
        # the default entry point takes the batched route implicitly
        np.testing.assert_allclose(loco.insights_matrix(X), batched, atol=0)


class TestGLMFamilies:
    def test_logistic_binary(self):
        X, y = _data(1)
        _assert_parity(OpLogisticRegression(max_iter=25).fit_arrays(X, y), X)

    def test_svc_margin(self):
        X, y = _data(2)
        _assert_parity(OpLinearSVC().fit_arrays(X, y), X)

    def test_softmax_multiclass(self):
        X, y = _data(3, classes=3)
        _assert_parity(OpLogisticRegression(max_iter=25).fit_arrays(X, y), X)

    def test_linear_regression(self):
        X, y = _data(4, classes=1)
        _assert_parity(OpLinearRegression().fit_arrays(X, y), X)

    def test_naive_bayes(self):
        X, y = _data(5)
        _assert_parity(OpNaiveBayes().fit_arrays(np.abs(X), y), np.abs(X))


class TestTreeFamilies:
    def test_rf_classifier_mean(self):
        X, y = _data(6)
        m = OpRandomForestClassifier(num_trees=5, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_gbt_classifier_margin(self):
        X, y = _data(7)
        m = OpGBTClassifier(max_iter=5, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_rf_regressor_mean(self):
        X, y = _data(8, classes=1)
        m = OpRandomForestRegressor(num_trees=5, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_gbt_regressor_sum(self):
        X, y = _data(9, classes=1)
        m = OpGBTRegressor(max_iter=5, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_xgb_regressor(self):
        X, y = _data(10, classes=1)
        m = OpXGBoostRegressor(num_round=5, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_xgb_softmax_multiclass(self):
        X, y = _data(11, classes=3)
        m = OpXGBoostClassifier(num_round=4, max_depth=3).fit_arrays(X, y)
        _assert_parity(m, X, tree=True)

    def test_inactive_features_have_zero_delta(self):
        X, y = _data(12, d=8)
        m = OpGBTClassifier(max_iter=3, max_depth=2).fit_arrays(X, y)
        from transmogrifai_tpu.insights.knockout import active_features
        act = set(active_features(m.feat, m.thresh_val).tolist())
        deltas = knockout_deltas(m, X, force_tree=True)
        for j in range(8):
            if j not in act:
                assert np.abs(deltas[:, j, :]).max() == 0.0

    def test_row_chunking_matches_single_chunk(self):
        X, y = _data(13, n=70)
        m = OpGBTClassifier(max_iter=3, max_depth=3).fit_arrays(X, y)
        full = knockout_deltas(m, X, force_tree=True)
        chunked = knockout_deltas(m, X, row_chunk=32,
                                  force_tree=True)   # 3 chunks, padded
        np.testing.assert_allclose(chunked, full, atol=1e-7)


class TestDispatch:
    def test_selected_model_unwraps(self):
        from transmogrifai_tpu.automl.selector import ModelSelectorSummary, \
            SelectedModel
        X, y = _data(14)
        inner = OpLogisticRegression(max_iter=25).fit_arrays(X, y)
        sel = SelectedModel(inner, ModelSelectorSummary(
            validation_type="cv", validation_parameters={},
            data_prep_parameters={}, data_prep_results={},
            evaluation_metric="au_pr", metric_larger_better=True,
            problem_type="binary", best_model_uid="u", best_model_name="lr",
            best_model_type="OpLogisticRegression", best_grid={}))
        np.testing.assert_allclose(knockout_deltas(sel, X),
                                   knockout_deltas(inner, X), atol=0)

    def test_unknown_model_falls_back_to_loop(self):
        class Opaque:
            def predict_arrays(self, X):
                s = X.sum(axis=1)
                return (s > 0).astype(np.float32), None, None

        X, _ = _data(15)
        assert knockout_deltas(Opaque(), X) is None
        deltas = RecordInsightsLOCO(model=Opaque()).insights_matrix(X)
        assert deltas.shape == (80, 6, 1)
