"""MLP classifier + isotonic calibration.

Mirrors reference suites OpMultilayerPerceptronClassifierTest.scala and
IsotonicRegressionCalibratorTest.scala.
"""
import numpy as np
import pytest

from transmogrifai_tpu.models.mlp import (
    IsotonicRegressionCalibrator, OpMultilayerPerceptronClassifier, pav_fit)
from transmogrifai_tpu.data.dataset import column_from_values
from transmogrifai_tpu.types import RealNN


class TestMLP:
    def test_solves_xor(self):
        rng = np.random.default_rng(0)
        X = rng.uniform(-1, 1, size=(1500, 2)).astype(np.float32)
        y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
        m = OpMultilayerPerceptronClassifier(
            hidden_layers=[16, 16], max_iter=600, step_size=0.05)
        model = m.fit_arrays(X, y)
        pred, raw, prob = model.predict_arrays(X)
        assert (pred == y).mean() > 0.95
        assert prob.shape == (1500, 2)
        assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)

    def test_multiclass(self):
        rng = np.random.default_rng(1)
        centers = rng.normal(scale=4, size=(3, 4))
        y = rng.integers(0, 3, 900)
        X = (centers[y] + rng.normal(size=(900, 4))).astype(np.float32)
        m = OpMultilayerPerceptronClassifier(hidden_layers=[12],
                                             max_iter=400)
        model = m.fit_arrays(X, y.astype(np.float32))
        pred, _, prob = model.predict_arrays(X)
        assert prob.shape[1] == 3
        assert (pred == y).mean() > 0.9

    def test_save_load(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(200, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        model = OpMultilayerPerceptronClassifier(
            hidden_layers=[5], max_iter=50).fit_arrays(X, y)
        restored = type(model).from_save_args(model.save_args())
        np.testing.assert_allclose(model.predict_arrays(X)[2],
                                   restored.predict_arrays(X)[2], atol=1e-6)


class TestIsotonic:
    def test_pav_monotone_and_fits_steps(self):
        x = np.array([1, 2, 3, 4, 5, 6], float)
        y = np.array([0.1, 0.0, 0.3, 0.2, 0.8, 0.9])
        bounds, values = pav_fit(x, y)
        assert (np.diff(values) >= 0).all()
        # pooled blocks: (0.1,0.0)->0.05, (0.3,0.2)->0.25
        assert values[0] == pytest.approx(0.05)
        assert 0.25 in np.round(values, 6)

    def test_calibrator_end_to_end(self):
        rng = np.random.default_rng(3)
        n = 2000
        score = rng.uniform(size=n)
        # true P(y|s) is monotone but nonlinear in score
        p = np.clip(score ** 2, 0, 1)
        label = (rng.uniform(size=n) < p).astype(float)
        cal = IsotonicRegressionCalibrator()
        lbl_col = column_from_values(RealNN, list(label))
        s_col = column_from_values(RealNN, list(score))
        model = cal.fit_columns(lbl_col, s_col)
        out = model.transform_columns(lbl_col, s_col)
        cali = np.asarray(out.data)
        # calibrated outputs monotone in score and close to s^2
        order = np.argsort(score)
        assert (np.diff(cali[order]) >= -1e-9).all()
        err = np.abs(cali - p).mean()
        assert err < 0.08

    def test_model_round_trip(self):
        from transmogrifai_tpu.models.mlp import IsotonicRegressionModel
        m = IsotonicRegressionModel(boundaries=np.array([0.0, 0.5]),
                                    values=np.array([0.2, 0.8]))
        r = type(m).from_save_args(m.save_args())
        assert r.transform_value(RealNN(0.0), RealNN(0.7)).value \
            == pytest.approx(0.8)
        assert r.transform_value(RealNN(0.0), RealNN(0.3)).value \
            == pytest.approx(0.2)
