"""Feature type hierarchy tests (reference FeatureTypeTest and friends)."""
import math

import numpy as np
import pytest

from transmogrifai_tpu import types as t


def test_real_empty_and_value():
    assert t.Real(None).is_empty
    assert t.Real(float("nan")).is_empty
    assert t.Real(3).value == 3.0
    assert t.Real(3.5).non_empty


def test_realnn_non_nullable():
    assert t.RealNN(1.0).value == 1.0
    with pytest.raises(ValueError):
        t.RealNN(None)


def test_binary_coercion():
    assert t.Binary(1).value is True
    assert t.Binary(0.0).value is False
    assert t.Binary(None).is_empty
    assert t.Binary(True).to_double() == 1.0


def test_integral_from_float():
    assert t.Integral(3.0).value == 3
    assert t.Integral(None).is_empty


def test_text_and_subtypes():
    assert t.Text("hi").value == "hi"
    assert t.Text("").is_empty
    assert t.Text(None).is_empty
    e = t.Email("ada@lovelace.org")
    assert e.prefix() == "ada" and e.domain() == "lovelace.org"
    assert t.Email("notanemail").domain() is None
    u = t.URL("https://x.org/a")
    assert u.domain() == "x.org" and u.protocol() == "https" and u.is_valid()
    assert not t.URL("garbage").is_valid()
    assert issubclass(t.PickList, t.Categorical)
    assert issubclass(t.PickList, t.Text)


def test_lists_sets_geo():
    assert t.TextList(["a", "b"]).value == ["a", "b"]
    assert t.TextList(None).is_empty
    assert len(t.MultiPickList({"x", "y"})) == 2
    g = t.Geolocation([37.4, -122.1, 5.0])
    assert g.lat == 37.4 and g.lon == -122.1 and g.accuracy == 5.0
    x, y, z = g.to_unit_sphere()
    assert math.isclose(x * x + y * y + z * z, 1.0, rel_tol=1e-9)
    with pytest.raises(ValueError):
        t.Geolocation([99.0, 0.0, 1.0])  # lat out of range
    assert t.Geolocation(None).is_empty


def test_opvector():
    v = t.OPVector([1.0, 2.0, 3.0])
    assert len(v) == 3
    w = v.combine(t.OPVector([4.0]))
    assert len(w) == 4
    assert t.OPVector(None).is_empty
    assert t.OPVector([1.0, 2.0]) == t.OPVector(np.array([1.0, 2.0]))


def test_maps():
    m = t.RealMap({"a": 1, "b": 2.5})
    assert m["a"] == 1.0 and m.get("b") == 2.5
    assert t.RealMap(None).is_empty
    b = t.BinaryMap({"k": 1})
    assert b.to_double_map() == {"k": 1.0}
    mp = t.MultiPickListMap({"k": ["x", "x", "y"]})
    assert mp["k"] == {"x", "y"}
    gm = t.GeolocationMap({"home": [1.0, 2.0, 3.0]})
    assert gm["home"] == [1.0, 2.0, 3.0]


def test_prediction():
    p = t.Prediction(prediction=1.0, raw_prediction=[0.2, 0.8],
                     probability=[0.3, 0.7])
    assert p.prediction == 1.0
    assert p.raw_prediction == [0.2, 0.8]
    assert p.probability == [0.3, 0.7]
    assert p.score == [0.3, 0.7]
    assert t.Prediction(prediction=2.0).score == [2.0]
    with pytest.raises(ValueError):
        t.Prediction({"nope": 1.0})


def test_type_registry():
    assert t.FeatureType.from_name("Real") is t.Real
    assert t.FeatureType.from_name("PickListMap") is t.PickListMap
    assert t.Real.is_subtype_of(t.OPNumeric)
    with pytest.raises(ValueError):
        t.FeatureType.from_name("Nope")


def test_defaults():
    assert t.default_of(t.Real).is_empty
    assert t.default_of(t.RealNN).value == 0.0
    assert t.default_of(t.Prediction).prediction == 0.0
    assert t.default_of(t.TextMap).is_empty
