"""Monoid aggregator + time-window semantics (reference
features/.../aggregators/: MonoidAggregatorDefaults, TimeBasedAggregator;
readers cutoff behavior DataReader.scala:219-246)."""
import numpy as np

from transmogrifai_tpu.features.aggregators import (
    FeatureAggregator, MonoidAggregatorDefaults, named_aggregator,
)
from transmogrifai_tpu.types import (
    Binary, Integral, MultiPickList, PickList, Real, RealMap, Text, TextList,
)


class TestMonoidDefaults:
    def test_real_sums(self):
        agg = MonoidAggregatorDefaults.aggregator_for(Real)
        assert agg.reduce([1.5, 2.5, None]) == 4.0

    def test_empty_reduce_is_empty_value(self):
        for tp in (Real, Integral, Text, PickList):
            agg = MonoidAggregatorDefaults.aggregator_for(tp)
            assert agg.reduce([]) is None or agg.reduce([]) in ((), {}, [])

    def test_binary_logical_or(self):
        agg = MonoidAggregatorDefaults.aggregator_for(Binary)
        assert agg.reduce([False, True, None]) is True
        assert agg.reduce([False, False]) is False

    def test_textlist_concatenates(self):
        agg = MonoidAggregatorDefaults.aggregator_for(TextList)
        out = agg.reduce([["a"], ["b", "c"]])
        assert list(out) == ["a", "b", "c"]

    def test_multipicklist_unions(self):
        agg = MonoidAggregatorDefaults.aggregator_for(MultiPickList)
        out = agg.reduce([{"x"}, {"y", "x"}])
        assert set(out) == {"x", "y"}

    def test_realmap_merges_last_wins(self):
        agg = MonoidAggregatorDefaults.aggregator_for(RealMap)
        out = agg.reduce([{"a": 1.0}, {"a": 2.0, "b": 3.0}])
        assert out["a"] == 2.0 and out["b"] == 3.0

    def test_named_min_max_first_last(self):
        assert named_aggregator("min", Real).reduce([3.0, 1.0, 2.0]) == 1.0
        assert named_aggregator("max", Real).reduce([3.0, 1.0, 2.0]) == 3.0
        assert named_aggregator("first", Real).reduce([3.0, 1.0]) == 3.0
        assert named_aggregator("last", Real).reduce([3.0, 1.0]) == 1.0


class TestTimeWindows:
    EVENTS = [(10.0, 100), (20.0, 200), (40.0, 400), (80.0, 800)]

    def test_predictor_keeps_at_or_before_cutoff(self):
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS, cutoff_time=400) == 70.0

    def test_response_keeps_after_cutoff(self):
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS, cutoff_time=400,
                          is_response=True) == 80.0

    def test_window_limits_lookback(self):
        # window 250ms before cutoff 800: keep events in (550, 800]
        fa = FeatureAggregator(Real, window_ms=250)
        assert fa.extract(self.EVENTS, cutoff_time=800) == 80.0
        # wider window picks up the 400-ms event too
        fa2 = FeatureAggregator(Real, window_ms=500)
        assert fa2.extract(self.EVENTS, cutoff_time=800) == 120.0

    def test_no_cutoff_aggregates_everything(self):
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS) == 150.0

    def test_untimed_events_always_kept(self):
        fa = FeatureAggregator(Real)
        # untimed event kept; the t=100 event is after cutoff 50 -> dropped
        assert fa.extract([(5.0, None), (7.0, 100)], cutoff_time=50) == 5.0
