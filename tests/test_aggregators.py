"""Monoid aggregator + time-window semantics (reference
features/.../aggregators/: MonoidAggregatorDefaults, TimeBasedAggregator;
readers cutoff behavior DataReader.scala:219-246)."""
import numpy as np
import pytest

from transmogrifai_tpu.features.aggregators import (
    FeatureAggregator, MonoidAggregatorDefaults, named_aggregator,
)
from transmogrifai_tpu.types import (
    Binary, Integral, MultiPickList, PickList, Real, RealMap, Text, TextList,
)


class TestMonoidDefaults:
    def test_real_sums(self):
        agg = MonoidAggregatorDefaults.aggregator_for(Real)
        assert agg.reduce([1.5, 2.5, None]) == 4.0

    def test_empty_reduce_is_empty_value(self):
        for tp in (Real, Integral, Text, PickList):
            agg = MonoidAggregatorDefaults.aggregator_for(tp)
            assert agg.reduce([]) is None or agg.reduce([]) in ((), {}, [])

    def test_binary_logical_or(self):
        agg = MonoidAggregatorDefaults.aggregator_for(Binary)
        assert agg.reduce([False, True, None]) is True
        assert agg.reduce([False, False]) is False

    def test_textlist_concatenates(self):
        agg = MonoidAggregatorDefaults.aggregator_for(TextList)
        out = agg.reduce([["a"], ["b", "c"]])
        assert list(out) == ["a", "b", "c"]

    def test_multipicklist_unions(self):
        agg = MonoidAggregatorDefaults.aggregator_for(MultiPickList)
        out = agg.reduce([{"x"}, {"y", "x"}])
        assert set(out) == {"x", "y"}

    def test_realmap_merges_per_key_sum(self):
        # reference UnionRealMap (Maps.scala:52): shared keys SUM
        agg = MonoidAggregatorDefaults.aggregator_for(RealMap)
        out = agg.reduce([{"a": 1.0}, {"a": 2.0, "b": 3.0}])
        assert out["a"] == 3.0 and out["b"] == 3.0

    def test_named_min_max_first_last(self):
        assert named_aggregator("min", Real).reduce([3.0, 1.0, 2.0]) == 1.0
        assert named_aggregator("max", Real).reduce([3.0, 1.0, 2.0]) == 3.0
        assert named_aggregator("first", Real).reduce([3.0, 1.0]) == 3.0
        assert named_aggregator("last", Real).reduce([3.0, 1.0]) == 1.0


class TestTimeWindows:
    EVENTS = [(10.0, 100), (20.0, 200), (40.0, 400), (80.0, 800)]

    def test_predictor_keeps_strictly_before_cutoff(self):
        # reference filterByDateWithCutoff (FeatureAggregator.scala:120):
        # predictors keep date < cutoff — the t=400 event is excluded
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS, cutoff_time=400) == 30.0

    def test_response_keeps_at_or_after_cutoff(self):
        # responses keep date >= cutoff (FeatureAggregator.scala:121)
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS, cutoff_time=400,
                          is_response=True) == 120.0

    def test_window_limits_lookback(self):
        # window 450ms before cutoff 800: keep events in [350, 800)
        fa = FeatureAggregator(Real, window_ms=450)
        assert fa.extract(self.EVENTS, cutoff_time=800) == 40.0
        # wider window picks up the earlier events too: [50, 800)
        fa2 = FeatureAggregator(Real, window_ms=750)
        assert fa2.extract(self.EVENTS, cutoff_time=800) == 70.0

    def test_response_window_limits_lookahead(self):
        # responses with a window keep cutoff <= date <= cutoff + window
        fa = FeatureAggregator(Real, window_ms=300)
        assert fa.extract(self.EVENTS, cutoff_time=200,
                          is_response=True) == 60.0   # t=200 + t=400

    def test_no_cutoff_aggregates_everything(self):
        fa = FeatureAggregator(Real)
        assert fa.extract(self.EVENTS) == 150.0

    def test_untimed_events_always_kept(self):
        fa = FeatureAggregator(Real)
        # untimed event kept; the t=100 event is after cutoff 50 -> dropped
        assert fa.extract([(5.0, None), (7.0, 100)], cutoff_time=50) == 5.0


class TestExpandedPalette:
    """Round-3 aggregator breadth (reference aggregators/ 9-file suite):
    means, mode, concat, logical ops, geographic midpoint, time-based
    first/last, per-key map value monoids."""

    def test_mean_and_percent_clamping(self):
        from transmogrifai_tpu.features.aggregators import mean_aggregator
        assert mean_aggregator().reduce([1.0, 2.0, None, 3.0]) == 2.0
        # Percent clamps into [0,1] BEFORE averaging (PercentPrepare)
        assert mean_aggregator(percent=True).reduce([0.5, 1.5, -0.5]) == \
            pytest.approx((0.5 + 1.0 + 0.0) / 3)

    def test_mode_picklist(self):
        from transmogrifai_tpu.features.aggregators import (
            MonoidAggregatorDefaults,
        )
        from transmogrifai_tpu.types import PickList
        agg = MonoidAggregatorDefaults.aggregator_for(PickList)
        assert agg.reduce(["a", "b", "b", None, "c"]) == "b"
        # deterministic tie-break: lexicographically smallest
        assert agg.reduce(["b", "a"]) == "a"

    def test_concat_text(self):
        from transmogrifai_tpu.features.aggregators import (
            MonoidAggregatorDefaults,
        )
        from transmogrifai_tpu.types import ComboBox, Text
        assert MonoidAggregatorDefaults.aggregator_for(Text).reduce(
            ["hello", None, "world"]) == "hello world"
        assert MonoidAggregatorDefaults.aggregator_for(ComboBox).reduce(
            ["a", "b"]) == "a,b"

    def test_logical_named(self):
        from transmogrifai_tpu.types import Binary
        assert named_aggregator("logical_and", Binary).reduce(
            [True, True, None]) is True
        assert named_aggregator("logical_and", Binary).reduce(
            [True, False]) is False
        assert named_aggregator("logical_xor", Binary).reduce(
            [True, True]) is False

    def test_geolocation_midpoint(self):
        from transmogrifai_tpu.features.aggregators import (
            MonoidAggregatorDefaults,
        )
        from transmogrifai_tpu.types import Geolocation
        agg = MonoidAggregatorDefaults.aggregator_for(Geolocation)
        # symmetric points on the equator: midpoint on the meridian between
        out = agg.reduce([[0.0, 10.0, 1.0], [0.0, -10.0, 3.0]])
        assert out[0] == pytest.approx(0.0, abs=1e-9)
        assert out[1] == pytest.approx(0.0, abs=1e-9)
        assert out[2] == pytest.approx(2.0)
        assert agg.reduce([None, None]) is None

    def test_time_based_first_last(self):
        from transmogrifai_tpu.types import Text
        # events arrive OUT of time order; first/last follow event time
        vals, times = ["mid", "oldest", "newest"], [200, 100, 300]
        assert named_aggregator("first", Text).reduce(vals, times) == "oldest"
        assert named_aggregator("last", Text).reduce(vals, times) == "newest"
        # no timestamps: encounter order
        assert named_aggregator("first", Text).reduce(["a", "b"]) == "a"
        assert named_aggregator("last", Text).reduce(["a", "b"]) == "b"
        # mixed: an untimed event never beats a timed one
        assert named_aggregator("first", Text).reduce(
            ["a", "b"], [100, None]) == "a"
        assert named_aggregator("last", Text).reduce(
            ["b", "a"], [None, 100]) == "a"

    def test_map_value_monoids(self):
        from transmogrifai_tpu.features.aggregators import (
            MonoidAggregatorDefaults,
        )
        from transmogrifai_tpu.types import (
            BinaryMap, DateMap, MultiPickListMap, TextMap,
        )
        assert MonoidAggregatorDefaults.aggregator_for(DateMap).reduce(
            [{"k": 100}, {"k": 50}])["k"] == 100
        assert MonoidAggregatorDefaults.aggregator_for(BinaryMap).reduce(
            [{"k": False}, {"k": True}])["k"] is True
        out = MonoidAggregatorDefaults.aggregator_for(
            MultiPickListMap).reduce([{"k": {"a"}}, {"k": {"b"}}])
        assert out["k"] == {"a", "b"}
        # free-text TextMap concats with " " (UnionConcatTextMap,
        # Maps.scala:145); structured subclasses like EmailMap use ","
        assert MonoidAggregatorDefaults.aggregator_for(TextMap).reduce(
            [{"k": "x"}, {"k": "y"}])["k"] == "x y"
        from transmogrifai_tpu.types import EmailMap
        assert MonoidAggregatorDefaults.aggregator_for(EmailMap).reduce(
            [{"k": "a@b.c"}, {"k": "d@e.f"}])["k"] == "a@b.c,d@e.f"

    def test_aggregate_reader_uses_event_times(self):
        """End to end: FeatureAggregator passes event times through, so
        a 'last' aggregate over out-of-order events is time-correct."""
        from transmogrifai_tpu.features.aggregators import FeatureAggregator
        from transmogrifai_tpu.types import Text
        fa = FeatureAggregator(type_cls=Text,
                               aggregator=named_aggregator("last", Text))
        out = fa.extract([("new", 300), ("old", 100)], cutoff_time=400)
        assert out == "new"
        # response keeps only post-cutoff events
        out = fa.extract([("pre", 100), ("post", 500)], cutoff_time=400,
                         is_response=True)
        assert out == "post"


def test_map_subclass_inherits_numeric_monoid():
    """issubclass dispatch: a user RealMap subclass sums per key instead
    of silently falling into string concat."""
    from transmogrifai_tpu.types import RealMap

    class SignalMap(RealMap):
        pass

    out = MonoidAggregatorDefaults.aggregator_for(SignalMap).reduce(
        [{"k": 1.0}, {"k": 2.0}])
    assert out["k"] == 3.0


def test_tuple_valued_raw_values_are_not_misparsed():
    """Geolocation values ARE tuples; reduce must never unpack them as
    (value, time) pairs."""
    from transmogrifai_tpu.types import Geolocation
    agg = MonoidAggregatorDefaults.aggregator_for(Geolocation)
    out = agg.reduce([(10.0, 20.0, 1.0)])
    assert out[0] == pytest.approx(10.0, abs=1e-6)
    assert out[1] == pytest.approx(20.0, abs=1e-6)
