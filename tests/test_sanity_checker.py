"""SanityChecker tests (mirror of core/src/test/.../preparators/
SanityCheckerTest.scala behaviors)."""
import numpy as np
import pytest

from transmogrifai_tpu.automl import SanityChecker, SanityCheckerModel
from transmogrifai_tpu.data.dataset import Column, column_from_values
from transmogrifai_tpu.data.vector import (
    NULL_STRING, VectorColumnMetadata, VectorMetadata,
)
from transmogrifai_tpu.types import ColumnKind, OPVector, RealNN


def _vec_col(X, meta=None):
    return Column(kind=ColumnKind.VECTOR, data=np.asarray(X, np.float32),
                  metadata=meta)


def _label_col(y):
    return column_from_values(RealNN, [float(v) for v in y])


def _meta(cols):
    return VectorMetadata(name="features", columns=cols)


def test_low_variance_column_dropped(rng):
    n = 500
    X = np.stack([rng.normal(size=n), np.full(n, 3.0)], axis=1)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    chk = SanityChecker(remove_bad_features=True)
    model = chk.fit_columns(_label_col(y), _vec_col(X))
    assert model.indices_to_keep == [0]
    assert "f1" in model.summary.dropped
    assert any("variance" in r for r in model.summary.drop_reasons["f1"])


def test_label_leakage_high_correlation_dropped(rng):
    n = 500
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    X = np.stack([rng.normal(size=n), y + 1e-4 * rng.normal(size=n)], axis=1)
    chk = SanityChecker(remove_bad_features=True, remove_feature_group=False)
    model = chk.fit_columns(_label_col(y), _vec_col(X))
    assert 1 not in model.indices_to_keep
    assert any("correlation" in r for r in model.summary.drop_reasons["f1"])


def test_no_removal_when_disabled(rng):
    n = 300
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    X = np.stack([rng.normal(size=n), y], axis=1)
    chk = SanityChecker()  # remove_bad_features defaults False (ref :728)
    model = chk.fit_columns(_label_col(y), _vec_col(X))
    assert model.indices_to_keep == [0, 1]
    assert model.summary.dropped == ["f1"]  # still recorded


def test_categorical_cramers_v_leak_dropped(rng):
    n = 600
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    # one-hot group perfectly predicting the label
    leak = np.stack([y, 1 - y], axis=1)
    noise = rng.normal(size=(n, 1))
    X = np.concatenate([noise, leak], axis=1)
    meta = _meta([
        VectorColumnMetadata("num", "Real", descriptor_value="v", index=0),
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="A", index=1),
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="B", index=2),
    ])
    chk = SanityChecker(remove_bad_features=True)
    model = chk.fit_columns(_label_col(y), _vec_col(X, meta))
    assert model.indices_to_keep == [0]
    gs = model.summary.categorical_stats
    assert len(gs) == 1
    assert gs[0]["cramers_v"] > 0.95
    assert model.metadata.size == 1
    assert model.metadata.columns[0].parent_feature_name == "num"


def test_cramers_v_known_value(rng):
    # independent uniform categorical vs label -> Cramer's V near 0
    n = 4000
    y = rng.integers(0, 2, size=n).astype(np.float32)
    g = rng.integers(0, 3, size=n)
    G = np.eye(3, dtype=np.float32)[g]
    X = np.concatenate([G, rng.normal(size=(n, 1))], axis=1)
    meta = _meta([
        VectorColumnMetadata("c", "PickList", grouping="c",
                             indicator_value=v, index=i)
        for i, v in enumerate("ABC")
    ] + [VectorColumnMetadata("num", "Real", descriptor_value="v", index=3)])
    chk = SanityChecker()
    model = chk.fit_columns(_label_col(y), _vec_col(X, meta))
    cv = model.summary.categorical_stats[0]["cramers_v"]
    assert cv < 0.05


def test_model_transform_and_jax_fn(rng):
    n = 50
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    model = SanityCheckerModel(indices_to_keep=[0, 2])
    out = model.transform_columns(_label_col(y), _vec_col(X))
    np.testing.assert_allclose(out.data, X[:, [0, 2]])
    fn = model.get_jax_fn()
    np.testing.assert_allclose(np.asarray(fn(y, X)), X[:, [0, 2]])


def test_rule_confidence_check(rng):
    # categorical value 'A' always => label 1: confidence 1.0, support ~0.5
    n = 400
    a = rng.uniform(size=n) < 0.5
    y = np.where(a, 1.0, (rng.uniform(size=n) < 0.5)).astype(np.float32)
    X = np.stack([a.astype(np.float32), 1 - a, rng.normal(size=n)], axis=1)
    meta = _meta([
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="A", index=0),
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="B", index=1),
        VectorColumnMetadata("num", "Real", descriptor_value="v", index=2),
    ])
    chk = SanityChecker(remove_bad_features=True, max_rule_confidence=0.9,
                        min_required_rule_support=0.3)
    model = chk.fit_columns(_label_col(y), _vec_col(X, meta))
    # whole cat group dropped (A triggers; B follows via group propagation)
    assert model.indices_to_keep == [2]


def test_sampling_fraction():
    chk = SanityChecker(check_sample=0.01)
    # lower limit pulls the fraction up for small data
    assert chk._fraction(500) == 1.0
    assert abs(chk._fraction(1_000_000) - 0.01) < 1e-9
    # upper limit caps huge data
    assert chk._fraction(1_000_000_000) <= 0.01
