"""End-to-end workflow engine tests.

Mirrors reference suites core/src/test/scala/com/salesforce/op/
{OpWorkflowTest,OpWorkflowModelReaderWriterTest}.scala and the canonical
helloworld flow (OpTitanicSimple.scala:94-149): raw features -> transmogrify
-> sanityCheck -> BinaryClassificationModelSelector -> train -> score ->
save/load -> score parity.
"""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.evaluators.evaluators import Evaluators
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.types import PickList, Real, RealNN
from transmogrifai_tpu.workflow import Workflow, WorkflowModel, compute_dag


def titanic_like_records(rng, n=300):
    """Synthetic records shaped like the Titanic demo (pclass/sex/age/fare)."""
    rows = []
    for i in range(n):
        sex = "female" if rng.uniform() < 0.4 else "male"
        pclass = int(rng.integers(1, 4))
        age = float(rng.normal(30, 12)) if rng.uniform() > 0.1 else None
        fare = float(abs(rng.normal(30, 20)))
        logit = (1.8 * (sex == "female") - 0.7 * (pclass - 2)
                 + (0.0 if age is None else -0.01 * (age - 30)) + 0.01 * fare - 0.4)
        p = 1 / (1 + np.exp(-logit))
        survived = float(rng.uniform() < p)
        rows.append({"survived": survived, "sex": sex, "pclass": str(pclass),
                     "age": age, "fare": fare})
    return rows


def build_features():
    survived = FeatureBuilder.RealNN("survived").extract(
        lambda r: r["survived"]).as_response()
    sex = FeatureBuilder.PickList("sex").extract(lambda r: r["sex"]).as_predictor()
    pclass = FeatureBuilder.PickList("pclass").extract(
        lambda r: r["pclass"]).as_predictor()
    age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
    fare = FeatureBuilder.Real("fare").extract(lambda r: r["fare"]).as_predictor()
    return survived, [sex, pclass, age, fare]


def small_selector():
    return BinaryClassificationModelSelector.with_cross_validation(
        model_types=[],
        models_and_parameters=[
            (OpLogisticRegression(), param_grid(reg_param=[0.01, 0.1]))],
        num_folds=3, seed=11)


@pytest.fixture
def trained(rng):
    rows = titanic_like_records(rng)
    survived, predictors = build_features()
    vec = transmogrify(predictors)
    checked = SanityChecker(min_variance=1e-6).set_input(
        survived, vec).get_output()
    pred = small_selector().set_input(survived, checked).get_output()
    wf = (Workflow()
          .set_reader(ListReader(rows))
          .set_result_features(pred))
    model = wf.train()
    return rows, survived, pred, model


def test_dag_layering():
    survived, predictors = build_features()
    vec = transmogrify(predictors)
    checked = SanityChecker().set_input(survived, vec).get_output()
    pred = small_selector().set_input(survived, checked).get_output()
    dag = compute_dag((pred,))
    # vectorizers -> combiner -> sanity checker -> selector = 4 layers
    assert len(dag.layers) == 4
    # selector is last, alone
    assert len(dag.layers[-1]) == 1
    # every vectorizer sits in the first layer
    assert len(dag.layers[0]) >= 2


def test_train_and_score_end_to_end(trained):
    rows, survived, pred, model = trained
    assert model.selector_summary() is not None
    scores = model.score(keep_raw_features=False)
    assert pred.name in scores.column_names()
    block = scores.data(pred.name)
    assert block.shape[0] == len(rows)

    metrics = model.evaluate(Evaluators.BinaryClassification.au_roc())
    # learnable synthetic signal: anything above 0.7 means the pipe works
    assert metrics["au_roc"] > 0.7

    pretty = model.summary_pretty()
    assert "Evaluated" in pretty and "OpLogisticRegression" in pretty


def test_score_without_labels(trained):
    rows, survived, pred, model = trained
    # scoring reader data has no 'survived' field at all
    unlabeled = [{k: v for k, v in r.items() if k != "survived"} for r in rows]
    scored = model.transform(ListReader(unlabeled).generate_dataset(
        [f for f in model.raw_features() if not f.is_response]))
    assert scored.data(pred.name).shape[0] == len(rows)


def test_save_load_score_parity(trained, tmp_path):
    rows, survived, pred, model = trained
    before = model.score().data(pred.name)

    path = str(tmp_path / "model")
    model.save(path)
    loaded = WorkflowModel.load(path)
    loaded.set_reader(ListReader(rows))
    after = loaded.score().data(pred.name)
    np.testing.assert_allclose(before, after, rtol=1e-6, atol=1e-6)

    # summaries survive the round trip
    assert loaded.selector_summary().best_model_name == \
        model.selector_summary().best_model_name
    assert loaded.sanity_checker_summary() is not None


def test_compute_data_up_to(rng):
    rows = titanic_like_records(rng, n=50)
    survived, predictors = build_features()
    vec = transmogrify(predictors)
    wf = Workflow().set_reader(ListReader(rows)).set_result_features(vec)
    ds = wf.compute_data_up_to(vec)
    assert vec.name in ds.column_names()
    assert ds.data(vec.name).ndim == 2


def test_missing_raw_column_fails(rng):
    survived, predictors = build_features()
    vec = transmogrify(predictors)
    ds = Dataset.from_features([("fare", Real, [1.0, 2.0])])
    wf = Workflow().set_input_dataset(ds).set_result_features(vec)
    with pytest.raises(ValueError, match="missing raw feature"):
        wf.train()


def test_with_model_stages_reuses_fitted_stages(monkeypatch):
    """Reference OpWorkflow.withModelStages:457: a second train() with the
    fitted model spliced in refits NOTHING that the model already fitted,
    and scores identically."""
    import numpy as np
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.data.dataset import Dataset
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.types import PickList, Real
    from transmogrifai_tpu.workflow.workflow import Workflow

    rng = np.random.default_rng(2)
    n = 300
    ds = Dataset.from_features([
        ("num", Real, rng.normal(size=n).tolist()),
        ("cat", PickList, [f"c{int(i)}" for i in
                           rng.integers(0, 5, size=n)]),
    ])
    num = FeatureBuilder.Real("num").extract(
        lambda r: r.get("num")).as_predictor()
    cat = FeatureBuilder.PickList("cat").extract(
        lambda r: r.get("cat")).as_predictor()
    vec = transmogrify([num, cat])
    wf = Workflow().set_input_dataset(ds).set_result_features(vec)
    model1 = wf.train()
    scored1 = model1.score(ds).column(vec.name).data

    from transmogrifai_tpu.stages.base import Estimator
    calls = []
    orig = Estimator.fit

    def spy(self, data):
        calls.append(self.uid)
        return orig(self, data)

    monkeypatch.setattr(Estimator, "fit", spy)
    model2 = wf.with_model_stages(model1).train()
    assert calls == [], f"estimators refit despite with_model_stages: {calls}"
    np.testing.assert_allclose(model2.score(ds).column(vec.name).data,
                               scored1, atol=1e-6)


def test_tiny_dataset_selector_trains_and_scores():
    """Folds > rows: empty validation folds must degrade gracefully
    (NaN fold metrics are excluded from the mean), not crash."""
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.testkit import TestFeatureBuilder
    from transmogrifai_tpu.types import Real, RealNN

    for n in (5, 3):
        ds, (fx, fy) = TestFeatureBuilder.build(
            ("x", Real, list(np.linspace(-1, 1, n))),
            ("label", RealNN, [float(i % 2) for i in range(n)]),
            response_index=1)
        vec = transmogrify([fx])
        pred = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3, models_and_parameters=[
                (OpLogisticRegression(max_iter=5), [{"reg_param": 0.1}])],
        ).set_input(fy, vec).get_output()
        model = Workflow().set_input_dataset(ds) \
            .set_result_features(pred).train()
        out = model.score(ds)
        assert out.column(pred.name).data.shape[0] == n
