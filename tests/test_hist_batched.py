"""Batched multi-(fold x lane) histogram pipeline: property tests.

The fused sweep reads the binned matrix ONCE per level for every
(fold x config) lane (ops/pallas_hist.hist_folds / route_hist); its
correctness contract is that batching must not change a result:

  1. the batched kernel == per-fold hist_pallas calls BIT-FOR-BIT in f32
     (each lane's contraction rows are disjoint — fusion is pure layout),
     across odd shapes: rows not divisible by the tile, n_slots 1,
     single fold, single lane;
  2. in bf16 contraction mode the batched and per-fold legs quantize
     identically (equal to each other bit-for-bit) and stay within the
     established 1e-3-AuPR-impact tolerance of the f32 leg;
  3. the fused route+hist pass == the separate route_pallas pass + the
     plain histogram of the surviving left children, bit-for-bit;
  4. the pure-jnp CPU fallback matches interpret-mode pallas up to f32
     summation order;
  5. the planner (plan_lane_chunk) honors every budget and the CPU
     fallback smoke runs on a tiny matrix — the tier-1 liveness check
     ci.sh exercises on every run (no TPU required).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from transmogrifai_tpu.ops import pallas_hist as PH


def _lanes_inputs(n, f, b, folds, n_slots, seed=0, channels=2,
                  integral=False):
    """integral=True draws small-integer payloads: every partial sum is
    exactly representable in f32 (and bf16), so equality assertions stay
    BIT-FOR-BIT no matter how the backend's gemm blocking associates the
    reduction — what's under test is lane/slot layout, not the backend's
    f32 rounding at different contraction shapes."""
    rng = np.random.default_rng(seed)
    Xb_t = jnp.asarray(rng.integers(0, b, size=(f, n)), jnp.int8)
    pay = (rng.integers(-8, 9, size=(folds * channels, n)) if integral
           else rng.normal(size=(folds * channels, n)))
    pay = jnp.asarray(pay, jnp.float32)
    # slot == n_slots exercises the dropped-row encoding in every shape
    slot = jnp.asarray(rng.integers(0, n_slots + 1, size=(folds, n)),
                       jnp.float32)
    return Xb_t, pay, slot


# odd shapes on purpose: ragged rows (n % blk != 0, multi-grid-step),
# n_slots 1, single fold, single lane, and a multi-lane fold-major stack
ODD_SHAPES = [
    pytest.param(PH._BLK + 17, 5, 8, 3, 4, id="ragged-rows"),
    pytest.param(257, 3, 4, 1, 1, id="single-fold-single-slot"),
    pytest.param(515, 6, 8, 5, 1, id="n-slots-1"),
    pytest.param(64, 2, 4, 1, 2, id="single-lane-tiny"),
    pytest.param(130, 4, 6, 6, 2, id="fold-x-config-lanes"),
]


@pytest.mark.parametrize("n,f,b,folds,n_slots", ODD_SHAPES)
def test_batched_matches_per_fold_f32_bitwise(n, f, b, folds, n_slots):
    Xb_t, pay, slot = _lanes_inputs(n, f, b, folds, n_slots,
                                    integral=True)
    C = pay.shape[0] // folds
    fused = PH.hist_pallas(Xb_t, pay, slot, n_slots=n_slots, n_bins=b,
                           interpret=True)
    for k in range(folds):
        one = PH.hist_pallas(Xb_t, pay[C * k:C * (k + 1)], slot[k:k + 1],
                             n_slots=n_slots, n_bins=b, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(fused[k * n_slots * C:(k + 1) * n_slots * C]),
            np.asarray(one))


@pytest.mark.parametrize("n,f,b,folds,n_slots", ODD_SHAPES)
def test_batched_matches_per_fold_f32_continuous(n, f, b, folds, n_slots):
    """Continuous payloads: same parity up to the backend's f32 gemm
    association (catches accumulation-scale bugs the exact-integer
    construction can't)."""
    Xb_t, pay, slot = _lanes_inputs(n, f, b, folds, n_slots)
    C = pay.shape[0] // folds
    fused = PH.hist_pallas(Xb_t, pay, slot, n_slots=n_slots, n_bins=b,
                           interpret=True)
    for k in range(folds):
        one = PH.hist_pallas(Xb_t, pay[C * k:C * (k + 1)], slot[k:k + 1],
                             n_slots=n_slots, n_bins=b, interpret=True)
        assert np.allclose(
            np.asarray(fused[k * n_slots * C:(k + 1) * n_slots * C]),
            np.asarray(one), atol=1e-4)


@pytest.mark.parametrize("n,f,b,folds,n_slots", ODD_SHAPES)
def test_batched_matches_per_fold_bf16(n, f, b, folds, n_slots):
    """bf16 contraction inputs: batched == per-fold bitwise (the lanes
    quantize independently), and both stay within the 1e-3-AuPR-impact
    tolerance of the f32 leg (BENCH_NOTES r4: <=0.4% relative on g/h)."""
    Xb_t, payi, slot = _lanes_inputs(n, f, b, folds, n_slots, seed=1,
                                     integral=True)
    _, payc, _ = _lanes_inputs(n, f, b, folds, n_slots, seed=1)
    C = payi.shape[0] // folds
    prev = PH._HIST_BF16
    try:
        PH.set_hist_bf16(True)
        fused = PH.hist_pallas(Xb_t, payi, slot, n_slots=n_slots,
                               n_bins=b, interpret=True, allow_bf16=True)
        for k in range(folds):
            one = PH.hist_pallas(Xb_t, payi[C * k:C * (k + 1)],
                                 slot[k:k + 1], n_slots=n_slots, n_bins=b,
                                 interpret=True, allow_bf16=True)
            np.testing.assert_array_equal(
                np.asarray(fused[k * n_slots * C:(k + 1) * n_slots * C]),
                np.asarray(one))
        quant = PH.hist_pallas(Xb_t, payc, slot, n_slots=n_slots,
                               n_bins=b, interpret=True, allow_bf16=True)
    finally:
        PH.set_hist_bf16(prev)
    f32 = PH.hist_pallas(Xb_t, payc, slot, n_slots=n_slots, n_bins=b,
                         interpret=True)
    ref = np.asarray(f32)
    scale = np.abs(ref).max() + 1.0
    assert np.allclose(np.asarray(quant), ref, atol=1e-2 * scale)


@pytest.mark.parametrize("n,f,b,folds,n_slots", ODD_SHAPES[:3])
def test_cpu_fallback_matches_interpret(n, f, b, folds, n_slots):
    """_hist_segment_jnp (the hist_folds CPU route) == interpret-mode
    pallas up to f32 summation order. (First three shapes only: the
    vmapped segment-sum's CPU compile is ~25s per novel fold count, and
    the dropped shapes add no new fallback code path.)"""
    Xb_t, pay, slot = _lanes_inputs(n, f, b, folds, n_slots, seed=2)
    want = PH.hist_pallas(Xb_t, pay, slot, n_slots=n_slots, n_bins=b,
                          interpret=True)
    got = PH._hist_segment_jnp(Xb_t, pay, slot, n_slots=n_slots, n_bins=b)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-4)


@pytest.mark.parametrize("derive_count", [False, True])
def test_derive_count_matches_streamed_channel(derive_count):
    """derive_count appends IN VMEM exactly the channel the tree path
    used to stream from HBM: count = (hessian > 0)."""
    n, f, b, folds, n_slots = 515, 4, 8, 3, 4
    rng = np.random.default_rng(3)
    Xb_t = jnp.asarray(rng.integers(0, b, size=(f, n)), jnp.int8)
    g = rng.normal(size=(folds, n)).astype(np.float32)
    h = np.where(rng.uniform(size=(folds, n)) < 0.3, 0.0,
                 rng.uniform(0.1, 1.0, size=(folds, n))).astype(np.float32)
    slot = jnp.asarray(rng.integers(0, n_slots, size=(folds, n)),
                       jnp.float32)
    pay2 = jnp.asarray(np.stack([g, h], axis=1).reshape(2 * folds, n))
    cnt = (h > 0).astype(np.float32)
    pay3 = jnp.asarray(np.stack([g, h, cnt], axis=1).reshape(3 * folds, n))
    if derive_count:
        got = PH.hist_pallas(Xb_t, pay2, slot, n_slots=n_slots, n_bins=b,
                             interpret=True, derive_count=True)
    else:
        got = PH._hist_segment_jnp(Xb_t, pay2, slot, n_slots=n_slots,
                                   n_bins=b, derive_count=True)
    want = PH.hist_pallas(Xb_t, pay3, slot, n_slots=n_slots, n_bins=b,
                          interpret=True)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-4)


@pytest.mark.parametrize("folds,n", [(3, 517), (1, 130)])
def test_route_hist_matches_separate_passes(folds, n):
    """One fused route+hist pass == route_pallas THEN hist_pallas of the
    left children, bit-for-bit on both outputs."""
    f, b, n_nodes = 5, 8, 4
    rng = np.random.default_rng(4)
    Xb_t = jnp.asarray(rng.integers(0, b, size=(f, n)), jnp.int8)
    pay = jnp.asarray(rng.normal(size=(2 * folds, n)), jnp.float32)
    node = jnp.asarray(rng.integers(0, n_nodes, size=(folds, n)),
                       jnp.float32)
    f_lvl = jnp.asarray(rng.integers(0, f, size=(folds, n_nodes)),
                        jnp.int32)
    t_lvl = jnp.asarray(rng.integers(0, b, size=(folds, n_nodes)),
                        jnp.int32)
    m_lvl = jnp.asarray(rng.integers(0, 2, size=(folds, n_nodes)),
                        jnp.int32)
    hist, new_node = PH.route_hist(Xb_t, pay, node, f_lvl, t_lvl, m_lvl,
                                   n_nodes=n_nodes, n_bins=b,
                                   interpret=True, derive_count=True)
    want_node = PH.route_pallas(Xb_t, node, f_lvl, t_lvl, m_lvl,
                                n_nodes=n_nodes, interpret=True)
    np.testing.assert_array_equal(np.asarray(new_node),
                                  np.asarray(want_node))
    # left rows keep their old node id as the next level's slot; right
    # rows drop (slot >= n_slots), same encoding hist_pallas pads with
    right = want_node - 2.0 * node
    slots = node + float(n_nodes) * right
    want_hist = PH.hist_pallas(Xb_t, pay, slots, n_slots=n_nodes,
                               n_bins=b, interpret=True, derive_count=True)
    np.testing.assert_array_equal(np.asarray(hist), np.asarray(want_hist))


def test_route_hist_cpu_fallback_decisions_match():
    """The jnp fallback of route_hist routes bitwise like interpret-mode
    pallas and its histogram matches within summation order."""
    f, b, n_nodes, folds, n = 4, 6, 2, 2, 261
    rng = np.random.default_rng(5)
    Xb_t = jnp.asarray(rng.integers(0, b, size=(f, n)), jnp.int8)
    pay = jnp.asarray(rng.normal(size=(2 * folds, n)), jnp.float32)
    node = jnp.asarray(rng.integers(0, n_nodes, size=(folds, n)),
                       jnp.float32)
    f_lvl = jnp.asarray(rng.integers(0, f, size=(folds, n_nodes)),
                        jnp.int32)
    t_lvl = jnp.asarray(rng.integers(0, b, size=(folds, n_nodes)),
                        jnp.int32)
    m_lvl = jnp.asarray(rng.integers(0, 2, size=(folds, n_nodes)),
                        jnp.int32)
    hist_i, node_i = PH.route_hist(Xb_t, pay, node, f_lvl, t_lvl, m_lvl,
                                   n_nodes=n_nodes, n_bins=b,
                                   interpret=True, derive_count=True)
    node_c = PH._route_level_jnp(Xb_t, node, f_lvl, t_lvl, m_lvl)
    np.testing.assert_array_equal(np.asarray(node_c), np.asarray(node_i))
    right = node_c - 2.0 * node
    hist_c = PH._hist_segment_jnp(Xb_t, pay,
                                  node + float(n_nodes) * right,
                                  n_slots=n_nodes, n_bins=b,
                                  derive_count=True)
    assert np.allclose(np.asarray(hist_c), np.asarray(hist_i), atol=1e-4)


class TestPlanner:
    """plan_lane_chunk: the single place tile/lane budgets are decided."""

    def test_respects_hbm_lane_budget(self, monkeypatch):
        monkeypatch.setenv("TMOG_GRID_FUSE_HBM_LANES", "20")
        monkeypatch.setenv("TMOG_GRID_FUSE_OUT_MB", "1000")
        # 16 configs x 5 folds = 80 lanes > 20: halve to 4 x 5 = 20
        assert PH.plan_lane_chunk(8, 9, 5, 16, 3) == 4

    def test_out_block_cap_halves_chunk(self, monkeypatch):
        monkeypatch.setenv("TMOG_GRID_FUSE_HBM_LANES", "4096")
        monkeypatch.setenv("TMOG_GRID_FUSE_OUT_MB", "8")
        full = PH.plan_fused_hist(64, 33, 16 * 5, 6).out_bytes / 1e6
        assert full > 8.0  # the cap must actually bind at 16 configs
        chunk = PH.plan_lane_chunk(64, 33, 5, 16, 6)
        assert 0 < chunk < 16
        assert PH.plan_fused_hist(64, 33, chunk * 5, 6).out_bytes / 1e6 \
            <= 8.0

    def test_zero_when_single_config_busts_caps(self, monkeypatch):
        # even ONE config's fold lanes violate the HBM budget -> 0, the
        # caller must take the per-config route (ADVICE r5: chunk==1
        # used to skip these caps entirely)
        monkeypatch.setenv("TMOG_GRID_FUSE_HBM_LANES", "3")
        assert PH.plan_lane_chunk(8, 9, 5, 16, 3) == 0

    def test_vmem_gate_matches_fused_hist_fits(self):
        for shape in [(64, 33, 5, 6), (300, 257, 5, 6), (8, 9, 1, 0)]:
            assert PH.plan_fused_hist(*shape).fits == \
                PH.fused_hist_fits(*shape)


def test_planner_cpu_smoke():
    """Tier-1 smoke (ci.sh runs this on every CPU pass): plan a tiny
    matrix, then drive hist_folds — which dispatches to the pure-jnp
    segment-sum fallback off-TPU — through the planned lane count."""
    n, f, b, folds, configs, depth = 96, 4, 7, 2, 3, 3
    chunk = PH.plan_lane_chunk(f, b, folds, configs, depth)
    assert chunk >= 1
    lanes = chunk * folds
    Xb_t, pay, slot = _lanes_inputs(n, f, b, lanes, 2, seed=6)
    out = PH.hist_folds(Xb_t, pay, slot, n_slots=2, n_bins=b,
                        derive_count=True)
    assert out.shape == (lanes * 2 * 3, f * b)
    assert bool(jnp.isfinite(out).all())
