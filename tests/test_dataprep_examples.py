"""The reference's data-prep examples, pinned to their PUBLISHED outputs.

JoinsAndAggregates.scala:127-135 and ConditionalAggregation.scala:105-113
print expected tables in their source; these tests run the ported flows on
the reference's own CSVs and assert those exact values. Skips when the
reference checkout is absent.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

REF = "/root/reference/helloworld/src/main/resources"
CLICKS = os.path.join(REF, "EmailDataset/Clicks.csv")
SENDS = os.path.join(REF, "EmailDataset/Sends.csv")
VISITS = os.path.join(REF, "WebVisitsDataset/WebVisits.csv")

needs_ref = pytest.mark.skipif(
    not all(map(os.path.isfile, (CLICKS, SENDS, VISITS))),
    reason="reference datasets not available")


def _rows(ds):
    from transmogrifai_tpu.readers.readers import KEY_COLUMN
    keys = list(ds.column(KEY_COLUMN).data)
    names = [n for n in ds.column_names() if n != KEY_COLUMN]
    return {k: {n: ds.column(n).data[i] for n in names}
            for i, k in enumerate(keys)}


@needs_ref
def test_joins_and_aggregates_matches_published_table():
    import op_dataprep
    rows = _rows(op_dataprep.joins_and_aggregates(CLICKS, SENDS))
    assert sorted(rows) == ["123", "456", "789"]
    ctr = [n for n in next(iter(rows.values())) if "ctr" in n][0]

    # published: |1.0|123|1.0|2.0|1.0|
    assert rows["123"]["numClicksYday"] == 2.0
    assert rows["123"]["numClicksTomorrow"] == 1.0
    assert rows["123"]["numSendsLastWeek"] == 1.0
    assert rows["123"][ctr] == 1.0
    # published: |0.0|456|1.0|0.0|0.0|
    assert rows["456"]["numClicksYday"] == 0.0
    assert rows["456"]["numClicksTomorrow"] == 1.0
    assert rows["456"]["numSendsLastWeek"] == 0.0
    assert rows["456"][ctr] == 0.0
    # published: |0.0|789|null|null|1.0| — the click-side nulls match; ctr
    # stays null here because the CURRENT reference DivideTransformer maps
    # an empty operand to an empty result (MathTransformers.scala:192-199),
    # so null/(1+1) cannot be 0.0 as the (older) comment table shows
    assert rows["789"]["numSendsLastWeek"] == 1.0
    assert np.isnan(rows["789"]["numClicksYday"])
    assert np.isnan(rows["789"]["numClicksTomorrow"])
    assert np.isnan(rows["789"][ctr])


@needs_ref
def test_conditional_aggregation_matches_published_table():
    import op_dataprep
    rows = _rows(op_dataprep.conditional_aggregation(VISITS))
    # opq never meets the landing-page condition -> dropped
    assert sorted(rows) == ["abc@salesforce.com", "lmn@salesforce.com",
                            "xyz@salesforce.com"]
    # published table, value for value
    assert rows["xyz@salesforce.com"]["numVisitsWeekPrior"] == 3.0
    assert rows["xyz@salesforce.com"]["numPurchasesNextDay"] == 1.0
    assert rows["lmn@salesforce.com"]["numVisitsWeekPrior"] == 0.0
    assert rows["lmn@salesforce.com"]["numPurchasesNextDay"] == 1.0
    assert rows["abc@salesforce.com"]["numVisitsWeekPrior"] == 1.0
    assert rows["abc@salesforce.com"]["numPurchasesNextDay"] == 0.0
