"""tmoglint v4: trace-contract (TRC001-005) + plan-precedence (PLN001).

The two contracts these rules prove — zero recompiles in steady state,
planner-arbitrated knob precedence — fail in the one way tier-1 cannot
catch: correct on the warm CPU test box, wrong on hardware. So the
tests here are adversarial about vacuity: every rule has known-bad
fixtures that MUST fire and known-good fixtures that MUST stay silent,
the repo-hot-paths-clean claim is asserted against the abstract
interpreter's own site counters (a scan that interpreted nothing does
not count as clean), and the canonical contract breaks are driven as
MUTATIONS of the real serve engine through the real CLI — the mutated
copy must go red, the restored copy green.
"""
import json
import os
import subprocess
import sys
import textwrap

from tools.tmoglint.core import (
    LintContext, expand_rule_selection, run_rules, scan_paths,
)
from tools.tmoglint.rules_trc import _governed_knobs
from tools.tmoglint.traceflow import (
    CHOKED, VARYING, hot_path_kind, is_test_path, trace_flow,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRC_ALL = ["TRC001", "TRC002", "TRC003", "TRC004", "TRC005"]


def lint(src: str, path: str = "ops/mod.py", rules=None):
    ctx = LintContext(path, textwrap.dedent(src))
    return run_rules([ctx], only=rules)


def lint_many(named_srcs, rules=None):
    ctxs = [LintContext(p, textwrap.dedent(s)) for p, s in named_srcs]
    return run_rules(ctxs, only=rules)


def rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# -- path scoping shared by the family ---------------------------------------

class TestScoping:
    def test_hot_path_kinds(self):
        assert hot_path_kind("serve/engine.py") == "request"
        assert hot_path_kind("fleet/router.py") == "request"
        assert hot_path_kind("parallel/tileplane.py") == "tile"
        assert hot_path_kind("readers/streaming.py") == "tile"
        # fit-time/offline neighbours are NOT hot paths: one compile per
        # dataset is the design there
        assert hot_path_kind("readers/readers.py") is None
        assert hot_path_kind("monitor/offline.py") is None
        assert hot_path_kind("ops/trees.py") is None
        assert hot_path_kind("tools/tmoglint/core.py") is None

    def test_tests_and_bench_excluded(self):
        assert is_test_path("tests/test_serve.py")
        assert is_test_path("bench.py")
        assert is_test_path("bench_serving.py")
        assert not is_test_path("serve/engine.py")


# -- TRC001: jit construction per call ---------------------------------------

class TestTRC001:
    def test_jit_minted_and_called_in_loop(self):
        out = lint("""
            import jax

            def sweep(fns, xs):
                for fn in fns:
                    g = jax.jit(fn)
                    xs = g(xs)
                return xs
        """, rules=["TRC001"])
        assert len(rule_lines(out, "TRC001")) == 1
        assert "inside the same loop" in out[0].message

    def test_inline_jit_call(self):
        out = lint("""
            import jax

            def apply(fn, x):
                return jax.jit(fn)(x)
        """, rules=["TRC001"])
        assert len(out) == 1
        assert "fresh jitted" in out[0].message

    def test_any_construction_in_request_path_function(self):
        out = lint("""
            import jax

            def score(self, x):
                g = jax.jit(lambda v: v + 1)
                return g(x)
        """, path="serve/engine.py", rules=["TRC001"])
        assert len(out) == 1
        assert "per-request" in out[0].message

    def test_module_level_jit_silent(self):
        out = lint("""
            import jax

            def _kernel(x):
                return x * 2

            kernel = jax.jit(_kernel)
        """, path="serve/engine.py", rules=["TRC001"])
        assert out == []

    def test_warmup_cache_store_in_loop_silent(self):
        # the prewarm idiom: minting per bucket into a cache is the
        # POINT of warmup — the program outlives the loop
        out = lint("""
            import jax

            def prewarm(self, fn, buckets):
                for b in buckets:
                    self._cache[b] = jax.jit(fn)
        """, rules=["TRC001"])
        assert out == []

    def test_test_paths_excluded(self):
        out = lint("""
            import jax

            def test_retrace_counter(fn, x):
                return jax.jit(fn)(x)
        """, path="tests/test_tracing.py", rules=["TRC001"])
        assert out == []


# -- TRC002: branch on derived/threaded traced values ------------------------

class TestTRC002:
    def test_branch_on_derived_local(self):
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                y = x * 2
                if y:
                    return y
                return x
        """, rules=["TRC002"])
        assert len(out) == 1
        assert "derived from traced values" in out[0].message

    def test_branch_on_threaded_helper_param(self):
        # the interprocedural case TPU002 cannot see: `v` is only a
        # tracer because f's call site passed one
        out = lint("""
            import jax

            def helper(v):
                if v:
                    return v
                return v + 1

            @jax.jit
            def f(x):
                return helper(x)
        """, rules=["TRC002"])
        assert len(out) == 1
        assert "bound to a tracer by a traced call site" in out[0].message

    def test_branch_through_bound_method_self_shift(self):
        # regression for the positional-binding bug the mutation drives
        # surfaced: `self.helper(x)` supplies the receiver implicitly,
        # so `x` binds to `v`, NOT to `self` — without the shift the
        # tracer binding lands on the wrong param and this goes silent
        out = lint("""
            import jax

            class Stage:
                def helper(self, v):
                    if v:
                        return v
                    return v + 1

                @jax.jit
                def f(self, x):
                    return self.helper(x)
        """, rules=["TRC002"])
        assert len(out) == 1
        assert "bound to a tracer" in out[0].message

    def test_static_argnames_param_silent(self):
        out = lint("""
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("mode",))
            def f(x, mode):
                if mode:
                    return x
                return -x
        """, rules=["TRC002"])
        assert out == []

    def test_backend_probe_silent(self):
        # jax.default_backend() is host introspection, not a tracer
        out = lint("""
            import jax

            @jax.jit
            def f(x):
                use_matmul = jax.default_backend() == "tpu"
                if use_matmul:
                    return x @ x
                return x
        """, rules=["TRC002"])
        assert out == []


# -- TRC003: call-varying shapes without a choke -----------------------------

class TestTRC003:
    def test_len_reaches_shape_in_request_path(self):
        out = lint("""
            import numpy as np

            def assemble(records):
                n = len(records)
                return np.zeros(n, np.float32)
        """, path="serve/engine.py", rules=["TRC003"])
        assert len(out) == 1
        assert "fresh XLA program" in out[0].message

    def test_two_hop_poison_through_helper(self):
        # the size crosses two plain python calls before the creator —
        # the call-site poisoning must ride the chain to a fixpoint
        out = lint("""
            import numpy as np

            def outer(records):
                n = len(records)
                return mid(n)

            def mid(n):
                return inner(n)

            def inner(n):
                return np.full(n, 0.0, np.float32)
        """, path="parallel/tileplane.py", rules=["TRC003"])
        assert len(out) == 1
        assert "np.full" in out[0].message

    def test_bound_method_two_hop_poison(self):
        # regression (pre-fix-failing): the engine's real chain is
        # score_batch -> self._assemble -> self._bucket_columns; the
        # receiver shift must hold or `bucket` never poisons
        out = lint("""
            import numpy as np

            class Engine:
                def score(self, records):
                    n = len(records)
                    return self._assemble(records, n)

                def _assemble(self, records, bucket):
                    return self._columns(bucket)

                def _columns(self, bucket):
                    return np.full(bucket, np.nan, np.float64)
        """, path="serve/engine.py", rules=["TRC003"])
        assert len(out) == 1

    def test_choked_through_bucket_ladder_silent(self):
        out = lint("""
            import numpy as np

            class Engine:
                def assemble(self, records):
                    n = self.pick_bucket(len(records))
                    return np.zeros(n, np.float32)
        """, path="serve/engine.py", rules=["TRC003"])
        assert out == []

    def test_planned_getter_chokes_silent(self):
        out = lint("""
            import numpy as np

            def tile(records):
                rows = planned_score_tile_rows(len(records))
                return np.empty(rows, dtype=object)
        """, path="readers/streaming.py", rules=["TRC003"])
        assert out == []

    def test_non_hot_path_silent(self):
        # fit-time code: one compile per dataset is the design
        out = lint("""
            import numpy as np

            def assemble(records):
                return np.zeros(len(records), np.float32)
        """, path="readers/readers.py", rules=["TRC003"])
        assert out == []


# -- TRC004: pytrees from unordered iteration --------------------------------

class TestTRC004:
    def test_comp_over_set_feeds_stack(self):
        out = lint("""
            import jax.numpy as jnp

            def pack(d):
                cols = [d[k] for k in set(d)]
                return jnp.stack(cols)
        """, rules=["TRC004"])
        assert len(out) == 1
        assert "sorted()" in out[0].message

    def test_loop_over_intersection_feeds_device_put(self):
        out = lint("""
            import jax

            def pack(d, wanted):
                vals = []
                for k in d.keys().intersection(wanted):
                    vals.append(d[k])
                return jax.device_put(vals)
        """, rules=["TRC004"])
        assert len(out) == 1

    def test_inline_comp_argument(self):
        out = lint("""
            import jax.numpy as jnp

            def pack(d):
                return jnp.stack([d[k] for k in set(d)])
        """, rules=["TRC004"])
        assert len(out) == 1

    def test_sorted_iteration_silent(self):
        out = lint("""
            import jax.numpy as jnp

            def pack(d):
                cols = [d[k] for k in sorted(set(d))]
                return jnp.stack(cols)
        """, rules=["TRC004"])
        assert out == []

    def test_host_only_consumer_silent(self):
        out = lint("""
            def total(d):
                return sum(d[k] for k in set(d))
        """, rules=["TRC004"])
        assert out == []


# -- TRC005: host sync on jit outputs in hot-path loops ----------------------

class TestTRC005:
    def test_item_in_tile_loop(self):
        out = lint("""
            import jax

            step = jax.jit(lambda c, x: c + x)

            def drain(tiles):
                total = 0.0
                for t in tiles:
                    r = step(total, t)
                    total = r.item()
                return total
        """, path="parallel/tileplane.py", rules=["TRC005"])
        assert len(out) == 1
        assert ".item()" in out[0].message

    def test_np_asarray_in_request_loop(self):
        out = lint("""
            import jax
            import numpy as np

            score = jax.jit(lambda x: x * 2)

            def serve(batches):
                outs = []
                for b in batches:
                    y = score(b)
                    outs.append(np.asarray(y))
                return outs
        """, path="serve/engine.py", rules=["TRC005"])
        assert len(out) == 1

    def test_sync_after_loop_silent(self):
        out = lint("""
            import jax

            step = jax.jit(lambda c, x: c + x)

            def drain(tiles):
                acc = 0.0
                for t in tiles:
                    acc = step(acc, t)
                return acc.item()
        """, path="parallel/tileplane.py", rules=["TRC005"])
        assert out == []

    def test_non_jit_value_silent(self):
        # device_put results are transfers, not jitted programs — the
        # tileplane's designed sync fences must stay silent
        out = lint("""
            import jax

            def feed(tiles):
                for t in tiles:
                    buf = jax.device_put(t)
                    buf.block_until_ready()
        """, path="parallel/tileplane.py", rules=["TRC005"])
        assert out == []

    def test_non_hot_path_silent(self):
        out = lint("""
            import jax

            step = jax.jit(lambda c, x: c + x)

            def fit(tiles):
                for t in tiles:
                    r = step(0.0, t)
                    print(r.item())
        """, path="ops/stats_engine.py", rules=["TRC005"])
        assert out == []


# -- PLN001: plan-precedence bypass ------------------------------------------

class TestPLN001:
    def test_function_level_read_of_governed_knob(self):
        out = lint("""
            import os

            def tile_budget():
                return int(os.environ.get("TMOG_TILE_MB", "32"))
        """, path="parallel/tileplane.py", rules=["PLN001"])
        assert len(out) == 1
        assert "TMOG_TILE_MB" in out[0].message
        assert "planned_" in out[0].message

    def test_subscript_read_in_serve_path(self):
        out = lint("""
            import os

            def ladder(self):
                return os.environ["TMOG_TREE_SCAN"]
        """, path="serve/engine.py", rules=["PLN001"])
        assert len(out) == 1

    def test_fallback_without_planner_consult_still_fires(self):
        # an except-arm read is only blessed when the TRY really was
        # the precedence ladder
        out = lint("""
            import os

            def rows(ds):
                try:
                    return ds.tile_rows
                except AttributeError:
                    return int(os.environ.get("TMOG_STATS_TILE_ROWS",
                                              "262144"))
        """, path="ops/stats_engine.py", rules=["PLN001"])
        assert len(out) == 1

    def test_module_level_pin_silent(self):
        out = lint("""
            import os

            _TREE_SCAN = os.environ.get("TMOG_TREE_SCAN", "1") != "0"
        """, path="ops/trees.py", rules=["PLN001"])
        assert out == []

    def test_planner_fallback_idiom_silent(self):
        out = lint("""
            import os

            def rows():
                try:
                    from ..planner import plan_fit
                    return plan_fit().stats_tile_rows
                except Exception:
                    return int(os.environ.get("TMOG_STATS_TILE_ROWS",
                                              "262144"))
        """, path="ops/stats_engine.py", rules=["PLN001"])
        assert out == []

    def test_ungoverned_knob_silent(self):
        out = lint("""
            import os

            def no_pallas():
                return os.environ.get("TMOG_NO_PALLAS", "") == "1"
        """, path="ops/pallas_hist.py", rules=["PLN001"])
        assert out == []

    def test_planner_and_tests_out_of_scope(self):
        src = """
            import os

            def resolve():
                return os.environ.get("TMOG_TILE_MB")
        """
        assert lint(src, path="planner/plan.py", rules=["PLN001"]) == []
        assert lint(src, path="tests/conftest.py", rules=["PLN001"]) == []

    def test_governed_set_parsed_from_scanned_planner(self):
        # a scanned planner/plan.py's _ENV_FOR dict REPLACES the frozen
        # fallback set — the governed set cannot drift from the planner
        planner = """
            _ENV_FOR = {"custom": "TMOG_CUSTOM_KNOB"}
        """
        reader = """
            import os

            def custom():
                return os.environ.get("TMOG_CUSTOM_KNOB")

            def tile_mb():
                return os.environ.get("TMOG_TILE_MB")
        """
        out = lint_many([("planner/plan.py", planner),
                         ("parallel/tileplane.py", reader)],
                        rules=["PLN001"])
        assert len(out) == 1
        assert "TMOG_CUSTOM_KNOB" in out[0].message


# -- suppression + family selection ------------------------------------------

class TestSuppressionAndSelection:
    def test_inline_disable_suppresses_trc(self):
        out = lint("""
            import jax

            def apply(fn, x):
                # tmoglint: disable=TRC001  one-shot tool, compile measured
                return jax.jit(fn)(x)
        """, rules=["TRC001"])
        assert out == []

    def test_disable_all_with_justification(self):
        out = lint("""
            import os

            def tile_budget():
                return os.environ.get("TMOG_TILE_MB")  # tmoglint: disable=PLN001  boot probe
        """, path="parallel/tileplane.py", rules=["PLN001"])
        assert out == []

    def test_family_prefix_expansion(self):
        assert expand_rule_selection(["TRC"]) == set(TRC_ALL)
        assert expand_rule_selection(["PLN"]) == {"PLN001"}
        got = expand_rule_selection(["TRC", "PLN"])
        assert got == set(TRC_ALL) | {"PLN001"}

    def test_list_rules_names_new_families(self):
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "--list-rules"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert proc.returncode == 0
        for rid in TRC_ALL + ["PLN001"]:
            assert rid in proc.stdout, rid

    def test_family_scope_composes_with_baseline_guard(self, tmp_path):
        """--rules TRC scopes the stale-entry check: another family's
        grandfathered entry is neither new nor stale, and a fixed TRC
        entry only goes stale under a TRC-selecting scan."""
        serve = tmp_path / "serve"
        serve.mkdir()
        (serve / "eng.py").write_text(textwrap.dedent("""
            import numpy as np

            def assemble(records):
                return np.zeros(len(records), np.float32)
        """))
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        base = tmp_path / "base.json"
        wrote = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "serve",
             "--root", str(tmp_path), "--baseline", str(base),
             "--write-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert wrote.returncode == 0, wrote.stdout + wrote.stderr
        entries = json.load(open(base))["findings"]
        assert any(e["rule"] == "TRC003" for e in entries), entries
        # PLN-scoped scan: the TRC003 entry is out of scope, not stale
        pln = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "serve",
             "--root", str(tmp_path), "--baseline", str(base),
             "--rules", "PLN"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert pln.returncode == 0, pln.stdout + pln.stderr
        # TRC-scoped scan sees it baselined: green
        trc = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "serve",
             "--root", str(tmp_path), "--baseline", str(base),
             "--rules", "TRC"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert trc.returncode == 0, trc.stdout + trc.stderr
        # fix the debt without regenerating: TRC-scoped scan goes stale
        (serve / "eng.py").write_text("x = 1\n")
        stale = subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "serve",
             "--root", str(tmp_path), "--baseline", str(base),
             "--rules", "TRC"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)
        assert stale.returncode == 1 and "stale" in stale.stdout


# -- CLI: parallel parity, SARIF, TMOG_LINT_JOBS -----------------------------

def _fixture_tree(tmp_path):
    """One TRC003 + one PLN001 finding, plus clean neighbours."""
    serve = tmp_path / "serve"
    serve.mkdir()
    (serve / "eng.py").write_text(textwrap.dedent("""
        import numpy as np

        def assemble(records):
            return np.zeros(len(records), np.float32)
    """))
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "knob.py").write_text(textwrap.dedent("""
        import os

        def tile_budget():
            return int(os.environ.get("TMOG_TILE_MB", "32"))
    """))
    (tmp_path / "clean.py").write_text("x = 1\n")


def _scan_json(tmp_path, *extra, env_extra=None):
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tmoglint", ".",
         "--root", str(tmp_path), "--no-baseline", *extra],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True)
    return proc


class TestCLI:
    def test_parallel_and_serial_reports_identical(self, tmp_path):
        _fixture_tree(tmp_path)
        outs = []
        for jobs in ("1", "2"):
            proc = _scan_json(tmp_path, "--jobs", jobs, "--format", "json",
                              "--rules", "TRC,PLN")
            assert proc.returncode == 1, proc.stdout + proc.stderr
            rep = json.loads(proc.stdout)
            outs.append([(f["rule"], f["path"], f["fingerprint"])
                         for f in rep["new"]])
        assert outs[0] == outs[1]
        assert {r for r, _, _ in outs[0]} == {"TRC003", "PLN001"}

    def test_sarif_round_trips_against_json_report(self, tmp_path):
        _fixture_tree(tmp_path)
        jproc = _scan_json(tmp_path, "--format", "json")
        sproc = _scan_json(tmp_path, "--format", "sarif")
        # same scan, same verdict, same exit code
        assert jproc.returncode == 1 and sproc.returncode == 1
        rep = json.loads(jproc.stdout)
        doc = json.loads(sproc.stdout)
        assert doc["version"] == "2.1.0"
        [run] = doc["runs"]
        # results are exactly the report's NEW findings
        assert [(r["ruleId"], r["fingerprints"]["tmoglint/v1"])
                for r in run["results"]] == \
            [(f["rule"], f["fingerprint"]) for f in rep["new"]]
        [loc] = run["results"][0]["locations"]
        f0 = rep["new"][0]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"] == f0["path"]
        assert phys["region"]["startLine"] == f0["line"]
        assert phys["region"]["startColumn"] == f0["col"] + 1
        # every used rule is declared with its registered doc line
        assert {r["id"] for r in run["tool"]["driver"]["rules"]} == \
            {f["rule"] for f in rep["new"]}
        # the rest of the JSON report rides the property bag verbatim
        props = run["properties"]
        for key in ("paths", "rules", "total_findings", "counts_by_rule",
                    "baselined", "stale_baseline_entries", "ok"):
            assert props[key] == rep[key], key
        # stats are per-run wall timings — two scans can't match on the
        # seconds, so round-trip the structure and the scan facts
        assert set(props["stats"]) == set(rep["stats"])
        assert props["stats"]["files"] == rep["stats"]["files"]
        assert props["stats"]["jobs"] == rep["stats"]["jobs"]

    def test_sarif_clean_scan_exits_zero(self, tmp_path):
        (tmp_path / "clean.py").write_text("x = 1\n")
        proc = _scan_json(tmp_path, "--format", "sarif")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        [run] = json.loads(proc.stdout)["runs"]
        assert run["results"] == []
        assert run["properties"]["ok"] is True

    def test_lint_jobs_env_knob(self, tmp_path):
        # >= 4 files: below that the pool is not worth starting and the
        # scan goes serial regardless of the requested width
        for i in range(5):
            (tmp_path / f"clean{i}.py").write_text("x = 1\n")
        # the knob pins the default pool width...
        proc = _scan_json(tmp_path, "--format", "json",
                          env_extra={"TMOG_LINT_JOBS": "2"})
        assert json.loads(proc.stdout)["stats"]["jobs"] == 2
        # ...an explicit --jobs still wins...
        proc = _scan_json(tmp_path, "--format", "json", "--jobs", "1",
                          env_extra={"TMOG_LINT_JOBS": "2"})
        assert json.loads(proc.stdout)["stats"]["jobs"] == 1
        # ...and an unparseable pin falls back to the cpu heuristic
        proc = _scan_json(tmp_path, "--format", "json",
                          env_extra={"TMOG_LINT_JOBS": "many"})
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["stats"]["jobs"] >= 1


# -- the repo's own hot paths: clean, and NON-vacuously ----------------------

class TestRepoScan:
    def test_repo_hot_paths_clean_nonvacuously(self):
        ctxs, errors = scan_paths(
            [os.path.join(REPO_ROOT, "transmogrifai_tpu")], REPO_ROOT)
        assert not errors
        findings = run_rules(ctxs, only=TRC_ALL + ["PLN001"])
        assert findings == [], [(f.rule, f.path, f.line) for f in findings]
        # ...and the interpreter actually interpreted: the clean verdict
        # is backed by discovered-and-analysed sites, not empty scans
        by_path = {c.path: c for c in ctxs}
        eng = by_path["transmogrifai_tpu/serve/engine.py"]
        eng_flow = trace_flow(eng)
        states = [st for _, _, st in eng_flow.shape_sites]
        assert eng_flow.stats["shape_sites"] >= 3, eng_flow.stats
        assert VARYING not in states, states
        # the choke is SEEN: score_batch's `bucket` is choked by
        # pick_bucket in the interpreted env (that is WHY the creator
        # sites downstream stay un-poisoned)
        score_batch = next(fi for fi in eng_flow.graph.all_funcs
                           if fi.name == "score_batch")
        assert eng_flow.shape_env(score_batch).get("bucket") == CHOKED
        totals = {"traced_funcs": 0, "jit_sites": 0, "call_bindings": 0,
                  "host_funcs": 0}
        for c in ctxs:
            fl = getattr(c, "_trace_flow", None)
            if fl is None:
                continue
            for k in totals:
                totals[k] += fl.stats[k]
        assert totals["traced_funcs"] > 20, totals
        assert totals["jit_sites"] > 5, totals
        assert totals["call_bindings"] > 50, totals
        assert totals["host_funcs"] > 10, totals

    def test_governed_set_comes_from_real_planner(self):
        ctxs, _ = scan_paths(
            [os.path.join(REPO_ROOT, "transmogrifai_tpu", "planner",
                          "plan.py")], REPO_ROOT)
        governed = _governed_knobs(ctxs)
        assert len(governed) >= 9
        assert {"TMOG_TILE_MB", "TMOG_TREE_SCAN",
                "TMOG_STATS_TILE_ROWS"} <= governed


# -- mutation drives: the canonical contract breaks, through the CLI ---------

def _drive(tmp_path, rule, family, mutate):
    """Copy the real serve engine aside, scan clean, apply `mutate`
    (old, new) to the copy, assert the CLI goes red naming `rule`, then
    restore and assert green again."""
    src = open(os.path.join(REPO_ROOT, "transmogrifai_tpu", "serve",
                            "engine.py")).read()
    serve = tmp_path / "serve"
    serve.mkdir(exist_ok=True)
    dst = serve / "engine.py"
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)

    def scan():
        return subprocess.run(
            [sys.executable, "-m", "tools.tmoglint", "serve/engine.py",
             "--root", str(tmp_path), "--no-baseline", "--rules", family],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True)

    dst.write_text(src)
    clean = scan()
    assert clean.returncode == 0, (rule, clean.stdout, clean.stderr)
    old, new = mutate
    assert src.count(old) == 1, f"engine anchor drifted: {old!r}"
    dst.write_text(src.replace(old, new))
    hit = scan()
    assert hit.returncode == 1, (rule, hit.stdout, hit.stderr)
    assert rule in hit.stdout, (rule, hit.stdout)
    dst.write_text(src)  # deleting the mutation restores the clean scan
    again = scan()
    assert again.returncode == 0, (rule, again.stdout, again.stderr)


class TestMutationDrives:
    ANCHOR = "        records = list(records)\n"

    def test_jit_into_score_batch_fires_trc001(self, tmp_path):
        _drive(tmp_path, "TRC001", "TRC",
               (self.ANCHOR,
                self.ANCHOR + "        _g = jax.jit(lambda v: v)\n"))

    def test_ladder_bypass_fires_trc003(self, tmp_path):
        # the ISSUE's canonical break: replace the bucket-ladder lookup
        # with the raw batch size — every distinct batch size becomes
        # its own XLA program, two helper hops away from the creator
        _drive(tmp_path, "TRC003", "TRC",
               ("        bucket = self.pick_bucket(n)\n",
                "        bucket = n\n"))

    def test_raw_governed_read_fires_pln001(self, tmp_path):
        _drive(tmp_path, "PLN001", "PLN",
               (self.ANCHOR,
                self.ANCHOR +
                '        _mb = os.environ.get("TMOG_TILE_MB")\n'))
