"""Round-4 dsl audit additions (VERDICT r3 #8): DateMap unit circle,
Prediction tupled/descale, map smart_vectorize routing, collection combine.

Reference: RichMapFeature.toUnitCircle:716, RichPredictionFeature
.tupled:1098/.descale:1113, RichMapFeature.smartVectorize:280,
RichFeaturesCollection.combine:76.
"""
import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, dsl
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.types import Prediction, RealNN
from transmogrifai_tpu.workflow import Workflow

HOUR_MS = 3_600_000


def _train(feature, rows):
    wf = Workflow().set_reader(ListReader(rows)).set_result_features(feature)
    return wf.train()


class TestDateMapUnitCircle:
    def test_per_key_sin_cos(self):
        rows = [{"dm": {"created": HOUR_MS * h, "seen": HOUR_MS * (h + 6)}}
                for h in range(24)]
        dm = FeatureBuilder.DateMap("dm").extract(
            lambda r: r["dm"]).as_predictor()
        vec = dm.to_unit_circle(time_period="HourOfDay")
        model = _train(vec, rows)
        ds = model.transform()
        out = ds.column(vec.name)
        assert out.data.shape == (24, 4)   # 2 keys x (sin, cos)
        names = out.metadata.column_names()
        assert any("created" in n and "sin" in n for n in names), names
        assert any("seen" in n and "cos" in n for n in names), names
        # row h: created at hour h -> sin/cos of 2*pi*h/24
        hours = np.arange(24)
        created_cols = [i for i, c in enumerate(out.metadata.columns)
                        if c.grouping == "created"]
        s, c = out.data[:, created_cols[0]], out.data[:, created_cols[1]]
        np.testing.assert_allclose(s, np.sin(2 * np.pi * hours / 24),
                                   atol=1e-5)
        np.testing.assert_allclose(c, np.cos(2 * np.pi * hours / 24),
                                   atol=1e-5)

    def test_missing_key_maps_to_origin(self):
        rows = [{"dm": {"created": HOUR_MS}}, {"dm": {"seen": HOUR_MS}}]
        dm = FeatureBuilder.DateMap("dm").extract(
            lambda r: r["dm"]).as_predictor()
        vec = dm.to_unit_circle_map()
        model = _train(vec, rows)
        out = model.transform().column(vec.name)
        seen_cols = [i for i, c in enumerate(out.metadata.columns)
                     if c.grouping == "seen"]
        assert out.data[0, seen_cols].tolist() == [0.0, 0.0]

    def test_block_listed_keys(self):
        rows = [{"dm": {"a": HOUR_MS, "b": HOUR_MS}}]
        dm = FeatureBuilder.DateMap("dm").extract(
            lambda r: r["dm"]).as_predictor()
        vec = dm.to_unit_circle_map(block_listed_keys=["b"])
        model = _train(vec, rows)
        out = model.transform().column(vec.name)
        assert out.data.shape == (1, 2)
        assert all(c.grouping == "a" for c in out.metadata.columns)


class TestPredictionDsl:
    def _pred_feature(self):
        rows = [{"p": {"prediction": float(i % 2),
                       "rawPrediction_0": -float(i), "rawPrediction_1": float(i),
                       "probability_0": 0.3, "probability_1": 0.7}}
                for i in range(4)]
        p = FeatureBuilder.Prediction("p").extract(
            lambda r: r["p"]).as_predictor() if hasattr(
            FeatureBuilder, "Prediction") else None
        if p is None:
            from transmogrifai_tpu.features.builder import FeatureBuilder as FB
            pytest.skip("no Prediction builder")
        return p, rows

    def test_tupled_flattens_to_three_features(self):
        p, rows = self._pred_feature()
        pred, raw, prob = p.tupled()
        assert pred.feature_type is RealNN
        model = _train(prob, rows)
        ds = model.transform()
        prob_col = ds.column(prob.name)
        np.testing.assert_allclose(np.asarray(prob_col.data, float)[0],
                                   [0.3, 0.7])
        model2 = _train(pred, rows)
        vals = model2.transform().column(pred.name).data
        np.testing.assert_allclose(np.asarray(vals, float),
                                   [0.0, 1.0, 0.0, 1.0])

    def test_descale_inverts_scaling(self):
        rows = [{"x": float(i), "p": {"prediction": (float(i) - 2.0) / 3.0}}
                for i in range(8)]
        x = FeatureBuilder.Real("x").extract(lambda r: r["x"]).as_predictor()
        # scale() records ScalingArgs; descale on the Prediction inverts it
        scaled = x.scale(scaling_type="linear", slope=1.0 / 3.0,
                         intercept=-2.0 / 3.0)
        p = FeatureBuilder.Prediction("p").extract(
            lambda r: r["p"]).as_predictor()
        descaled = p.descale(scaled, scaler=scaled.origin_stage)
        model = _train(descaled, rows)
        vals = np.asarray(model.transform().column(descaled.name).data, float)
        np.testing.assert_allclose(vals, np.arange(8, dtype=float), atol=1e-5)


class TestCollectionOps:
    def test_module_level_combine(self):
        rows = [{"a": 1.0, "b": 2.0}]
        a = FeatureBuilder.Real("a").extract(lambda r: r["a"]).as_predictor()
        b = FeatureBuilder.Real("b").extract(lambda r: r["b"]).as_predictor()
        va, vb = a.vectorize(), b.vectorize()
        both = dsl.combine([va, vb])
        model = _train(both, rows)
        out = model.transform().column(both.name)
        assert out.data.shape[1] == va_width(model, va) + va_width(model, vb)

    def test_smart_vectorize_routes_text_maps(self):
        rows = [{"tm": {"k1": "alpha", "k2": "beta"}},
                {"tm": {"k1": "alpha"}}]
        tm = FeatureBuilder.TextMap("tm").extract(
            lambda r: r["tm"]).as_predictor()
        vec = tm.smart_vectorize(top_k=5, min_support=1)
        model = _train(vec, rows)
        out = model.transform().column(vec.name)
        assert out.data.shape[0] == 2 and out.data.shape[1] >= 2
        groupings = {c.grouping for c in out.metadata.columns}
        assert {"k1", "k2"} <= groupings


def va_width(model, feat):
    return model.transform().column(feat.name).data.shape[1]
