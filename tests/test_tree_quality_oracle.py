"""External model-quality oracle: the tree family vs scikit-learn's
HistGradientBoosting (the natural stand-in for the reference's JNI
XGBoost, same histogram-GBT algorithm family) and the GLM family vs
sklearn LogisticRegression. The reference contract is statistical — the
BASELINE AuPR-within-1e-3 clause is device-vs-host for the SAME model;
across independent implementations with different binning/regularization
details the honest contract is holdout-metric parity within a stated
tolerance on real datasets.

Tolerances (stated): AuPR/AuROC within 0.02 absolute on holdout;
regression RMSE within 10% relative. Datasets cover weights and missing
values (both frameworks handle NaN natively: ours bins NaN to bin 0,
HistGradientBoosting routes NaN per-split)."""
import numpy as np
import pytest

from sklearn.datasets import load_breast_cancer, load_diabetes
from sklearn.ensemble import (
    HistGradientBoostingClassifier, HistGradientBoostingRegressor,
)
from sklearn.linear_model import LogisticRegression
from sklearn.metrics import average_precision_score, roc_auc_score

from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.trees import (
    OpXGBoostClassifier, OpXGBoostRegressor,
)

AUPR_TOL = 0.02
AUROC_TOL = 0.02
RMSE_REL_TOL = 0.10

_GBT = dict(num_round=80, eta=0.1, max_depth=5, max_bins=64, reg_lambda=1.0)
_HGB = dict(max_iter=80, learning_rate=0.1, max_depth=5, max_bins=63,
            l2_regularization=1.0, early_stopping=False, random_state=0)


def _split(X, y, seed=0, frac=0.25, w=None):
    rng = np.random.default_rng(seed)
    n = len(y)
    idx = rng.permutation(n)
    cut = int(n * frac)
    te, tr = idx[:cut], idx[cut:]
    if w is not None:
        return (X[tr], y[tr], X[te], y[te], w[tr])
    return (X[tr], y[tr], X[te], y[te])


def _our_margin(model, X):
    _, raw, prob = model.predict_arrays(X)
    if prob is not None:
        p = np.asarray(prob)
        return p[:, 1] if p.ndim == 2 else p
    return np.asarray(raw)[:, 0]


def test_gbt_classifier_breast_cancer():
    d = load_breast_cancer()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32))
    ours = OpXGBoostClassifier(**_GBT).fit_arrays(Xtr, ytr)
    s_ours = _our_margin(ours, Xte)
    ref = HistGradientBoostingClassifier(**_HGB).fit(Xtr, ytr)
    s_ref = ref.predict_proba(Xte)[:, 1]
    aupr_o = average_precision_score(yte, s_ours)
    aupr_r = average_precision_score(yte, s_ref)
    assert abs(aupr_o - aupr_r) <= AUPR_TOL, (aupr_o, aupr_r)
    auroc_o = roc_auc_score(yte, s_ours)
    auroc_r = roc_auc_score(yte, s_ref)
    assert abs(auroc_o - auroc_r) <= AUROC_TOL, (auroc_o, auroc_r)


def test_gbt_classifier_missing_values_and_weights():
    rng = np.random.default_rng(4)
    n = 4000
    X = rng.normal(size=(n, 8)).astype(np.float32)
    margin = (np.where(X[:, 0] > 0, 1.2, -0.8) + 0.8 * X[:, 1] * X[:, 2]
              + 0.5 * np.sin(2 * X[:, 3]))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    # 15% missing in half the columns; integer-ish weights
    miss = rng.uniform(size=X.shape) < 0.15
    miss[:, 4:] = False
    X[miss] = np.nan
    w = rng.integers(1, 4, size=n).astype(np.float32)
    Xtr, ytr, Xte, yte, wtr = _split(X, y, seed=1, w=w)
    ours = OpXGBoostClassifier(**_GBT).fit_arrays(Xtr, ytr, w=wtr)
    s_ours = _our_margin(ours, Xte)
    ref = HistGradientBoostingClassifier(**_HGB).fit(
        Xtr, ytr, sample_weight=wtr)
    s_ref = ref.predict_proba(Xte)[:, 1]
    aupr_o = average_precision_score(yte, s_ours)
    aupr_r = average_precision_score(yte, s_ref)
    assert abs(aupr_o - aupr_r) <= AUPR_TOL, (aupr_o, aupr_r)


def test_gbt_regressor_diabetes():
    d = load_diabetes()
    Xtr, ytr, Xte, yte = _split(d.data.astype(np.float32),
                                d.target.astype(np.float32), seed=2)
    ours = OpXGBoostRegressor(**_GBT).fit_arrays(Xtr, ytr)
    pred_o, _, _ = ours.predict_arrays(Xte)
    ref = HistGradientBoostingRegressor(**_HGB).fit(Xtr, ytr)
    pred_r = ref.predict(Xte)
    rmse_o = float(np.sqrt(np.mean((np.asarray(pred_o) - yte) ** 2)))
    rmse_r = float(np.sqrt(np.mean((pred_r - yte) ** 2)))
    assert rmse_o <= rmse_r * (1 + RMSE_REL_TOL), (rmse_o, rmse_r)


def test_gbt_regressor_piecewise_missing():
    rng = np.random.default_rng(7)
    n = 3000
    X = rng.uniform(-2, 2, size=(n, 6)).astype(np.float32)
    y = (np.where(X[:, 0] > 0.5, 3.0, 0.0) + X[:, 1] ** 2
         - 2.0 * (X[:, 2] < -1) + 0.1 * rng.normal(size=n)).astype(np.float32)
    X[rng.uniform(size=X.shape) < 0.1] = np.nan
    Xtr, ytr, Xte, yte = _split(X, y, seed=3)
    ours = OpXGBoostRegressor(**_GBT).fit_arrays(Xtr, ytr)
    pred_o, _, _ = ours.predict_arrays(Xte)
    ref = HistGradientBoostingRegressor(**_HGB).fit(Xtr, ytr)
    pred_r = ref.predict(Xte)
    rmse_o = float(np.sqrt(np.mean((np.asarray(pred_o) - yte) ** 2)))
    rmse_r = float(np.sqrt(np.mean((pred_r - yte) ** 2)))
    assert rmse_o <= rmse_r * (1 + RMSE_REL_TOL), (rmse_o, rmse_r)


def test_glm_vs_sklearn_logistic():
    d = load_breast_cancer()
    X = d.data.astype(np.float32)
    # standardize for sklearn conditioning; ours standardizes internally
    X = (X - X.mean(0)) / X.std(0)
    Xtr, ytr, Xte, yte = _split(X, d.target.astype(np.float32), seed=5)
    ours = OpLogisticRegression(max_iter=60, reg_param=1e-3).fit_arrays(
        Xtr, ytr)
    s_ours = np.asarray(Xte @ np.asarray(ours.beta) + float(ours.intercept))
    # C = 1 / (n * reg) matches our per-row-mean loss scaling
    ref = LogisticRegression(C=1.0 / (len(ytr) * 1e-3), max_iter=2000)
    ref.fit(Xtr, ytr)
    s_ref = Xte @ ref.coef_[0] + ref.intercept_[0]
    auroc_o = roc_auc_score(yte, s_ours)
    auroc_r = roc_auc_score(yte, s_ref)
    assert abs(auroc_o - auroc_r) <= AUROC_TOL, (auroc_o, auroc_r)
    # coefficient geometry agrees (direction cosine)
    b_o = np.asarray(ours.beta, np.float64)
    b_r = ref.coef_[0]
    cos = b_o @ b_r / (np.linalg.norm(b_o) * np.linalg.norm(b_r) + 1e-12)
    assert cos > 0.95, cos
