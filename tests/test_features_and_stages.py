"""Feature DAG + stage base contract tests.

Mirrors the reference contract suites: OpPipelineStageSpec (naming/copy),
OpTransformerSpec (row-level == columnar), FeatureLike graph ops.
"""
import numpy as np
import pytest

from transmogrifai_tpu import (
    Binary, Dataset, Feature, FeatureBuilder, JaxTransformer, LambdaTransformer,
    PickList, Real, RealNN, Text, unary_transformer,
)
from transmogrifai_tpu.data.dataset import column_from_values


def _toy_features():
    age = FeatureBuilder.Real("age").extract(lambda r: r.get("age")).as_predictor()
    sex = FeatureBuilder.PickList("sex").extract(lambda r: r.get("sex")).as_predictor()
    y = FeatureBuilder.RealNN("label").extract(lambda r: float(r["label"])).as_response()
    return age, sex, y


def test_feature_builder_basics():
    age, sex, y = _toy_features()
    assert age.name == "age" and age.feature_type is Real and not age.is_response
    assert sex.feature_type is PickList
    assert y.is_response and y.feature_type is RealNN
    assert age.is_raw
    assert age.origin_stage.extract({"age": 31}) == 31.0


def test_transform_with_builds_dag():
    age, sex, y = _toy_features()
    doubler = JaxTransformer("double", fn=lambda x: x * 2.0,
                             input_types=(Real,), output_type=Real)
    age2 = age.transform_with(doubler)
    assert age2.parents == (age,)
    assert age2.origin_stage is doubler
    assert not age2.is_raw
    assert age2.feature_type is Real
    assert "double" in age2.name
    # response propagation
    lab2 = y.transform_with(JaxTransformer("noop", fn=lambda x: x,
                                           input_types=(RealNN,), output_type=RealNN))
    assert lab2.is_response


def test_parent_stages_and_history():
    age, sex, y = _toy_features()
    s1 = JaxTransformer("p1", fn=lambda x: x + 1, input_types=(Real,), output_type=Real)
    s2 = JaxTransformer("p2", fn=lambda x: x * 3, input_types=(Real,), output_type=Real)
    f2 = age.transform_with(s1).transform_with(s2)
    dists = f2.parent_stages()
    assert dists[s2] == 0 and dists[s1] == 1
    h = f2.history()
    assert h.origin_features == ("age",)
    assert len(f2.raw_features()) == 1


def test_lambda_transformer_row_equals_columnar():
    ds = Dataset.from_features([("t", Text, ["a", "bb", None, "cccc"])])
    lengther = unary_transformer(
        "len", lambda v: None if v.is_empty else float(len(v.value)), Text, Real)
    txt = FeatureBuilder.Text("t").as_predictor()
    out_feat = txt.transform_with(lengther)
    out = lengther.transform(ds)
    got = out.data(out_feat.name)
    assert np.isnan(got[2])
    assert list(got[[0, 1, 3]]) == [1.0, 2.0, 4.0]
    # row-level protocol matches
    assert lengther.transform_keyvalue({"t": "bb"}) == 2.0
    assert lengther.transform_keyvalue({"t": None}) is None


def test_jax_transformer_columnar_and_rowwise_agree():
    ds = Dataset.from_features([("x", Real, [1.0, 2.0, None, 4.0])])
    sq = JaxTransformer("sq", fn=lambda x: x * x, input_types=(Real,), output_type=Real)
    x = FeatureBuilder.Real("x").as_predictor()
    sq.set_input(x)
    col = sq.transform_columns(ds.column("x"))
    assert list(col.data[[0, 1, 3]]) == [1.0, 4.0, 16.0]
    assert np.isnan(col.data[2])
    assert sq.transform_value(Real(3.0)).value == 9.0
    assert sq.transform_value(Real(None)).is_empty


def test_stage_copy_preserves_params():
    sq = JaxTransformer("sq", fn=lambda x: x * x, input_types=(Real,), output_type=Real)
    c = sq.copy()
    assert c.uid != sq.uid
    assert c.operation_name == "sq"


def test_type_checking():
    age, sex, y = _toy_features()
    sq = JaxTransformer("sq", fn=lambda x: x, input_types=(Real,), output_type=Real)
    with pytest.raises(TypeError):
        sq.set_input(sex)  # PickList is not Real


def test_from_rows_inference():
    rows = [
        {"age": 31.0, "sex": "m", "n": 3, "flag": True, "label": 1},
        {"age": None, "sex": "f", "n": 5, "flag": False, "label": 0},
    ]
    y, feats = FeatureBuilder.from_rows(rows, response="label")
    by_name = {f.name: f for f in feats}
    assert by_name["age"].feature_type.__name__ == "Real"
    assert by_name["sex"].feature_type.__name__ == "PickList"
    assert by_name["n"].feature_type.__name__ == "Integral"
    assert by_name["flag"].feature_type.__name__ == "Binary"
    assert y.feature_type.__name__ == "RealNN" and y.is_response


def test_dataset_ops():
    ds = Dataset.from_features([
        ("x", Real, [1.0, None, 3.0]),
        ("s", Text, ["a", None, "c"]),
    ])
    assert ds.n_rows == 3
    assert set(ds.column_names()) == {"x", "s"}
    sub = ds.take(np.array([0, 2]))
    assert sub.n_rows == 2 and sub.data("s")[1] == "c"
    assert ds.select(["x"]).column_names() == ["x"]
