"""Model-quality parity on the canonical reference datasets.

VERDICT r3 #4: the example tests asserted presence ("Selected" in output),
not quality. These pin the canonical flows to the reference's PUBLISHED
numbers — Titanic holdout AuROC 0.8822 / AuPR 0.8225
(/root/reference/README.md:84-96, the OpTitanicSimple run) — within a
tolerance that covers split/seed/solver differences (different holdout draw
of ~90 rows alone gives ~±0.03).

The datasets are read directly (read-only) from the reference resource
tree; nothing is copied into this repo. Tests skip when the reference
checkout is absent.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

REF = "/root/reference/helloworld/src/main/resources"
TITANIC = os.path.join(REF, "TitanicDataset/TitanicPassengersTrainData.csv")
IRIS = os.path.join(REF, "IrisDataset/iris.data")
HOUSING = os.path.join(REF, "BostonDataset/housing.data")

needs_ref = pytest.mark.skipif(
    not all(map(os.path.isfile, (TITANIC, IRIS, HOUSING))),
    reason="reference datasets not available")


@needs_ref
def test_titanic_quality_matches_published_reference_run():
    import op_titanic_simple as t
    from transmogrifai_tpu.readers.readers import CSVReader

    wf, _ = t.build_workflow()
    model = wf.set_reader(
        CSVReader(TITANIC, columns=t.PASSENGER_COLUMNS)).train()
    s = model.selector_summary()
    hold, train = s.holdout_evaluation, s.train_evaluation
    # published holdout: AuROC 0.8822, AuPR 0.8225; train: 0.8767 / 0.8503
    assert abs(hold["au_roc"] - 0.8822) <= 0.05, hold
    assert hold["au_pr"] >= 0.8225 - 0.06, hold
    assert abs(train["au_roc"] - 0.8767) <= 0.05, train
    assert train["au_pr"] >= 0.8503 - 0.06, train


@needs_ref
def test_iris_quality_on_real_data():
    import op_iris
    model = op_iris.main([IRIS])
    s = model.selector_summary()
    # no published reference numbers for OpIris; floors from a measured run
    # of this flow (holdout f1 0.867 on the DataCutter 20% split) with slack
    # for seed drift. petalWidth is dropped by the checker's max-correlation
    # rule (|corr with label| > 0.95) exactly as the reference's would.
    assert s.holdout_evaluation["f1"] >= 0.80, s.holdout_evaluation
    assert s.train_evaluation["f1"] >= 0.93, s.train_evaluation


@needs_ref
def test_boston_quality_on_real_data():
    import op_boston
    model = op_boston.main([HOUSING])
    s = model.selector_summary()
    # no published reference numbers for OpBoston; floors from a measured
    # run of this flow (holdout RMSE 2.96 / R^2 0.856) with slack
    assert s.holdout_evaluation["rmse"] <= 4.5, s.holdout_evaluation
    assert s.holdout_evaluation["r2"] >= 0.70, s.holdout_evaluation
