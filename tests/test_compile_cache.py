"""Persistent XLA compilation cache (utils/platform.enable_compilation_cache,
wired at package import): compiled executables must land in the cache dir so
cold processes (examples, CI, local serving starts) stop re-paying compiles."""
import os
import subprocess
import sys

import pytest


def test_cache_config_applied():
    opt = os.environ.get("TMOG_COMPILE_CACHE", "").strip().lower()
    if opt in ("0", "off", "none", "disable"):
        pytest.skip("cache opted out via TMOG_COMPILE_CACHE")
    import jax

    import transmogrifai_tpu  # noqa: F401 — import wires the cache

    loc = jax.config.jax_compilation_cache_dir
    if not loc:
        pytest.skip("cache dir not configured (read-only home)")
    assert os.path.isdir(loc)


def test_cache_populates_and_hits(tmp_path):
    """A fresh cache dir gains entries on first compile; a second process
    with the same program loads from it (observable: entry count stable,
    and the second run is not slower — the strong timing assertion lives
    in bench.py where the clock is controlled)."""
    env = dict(os.environ)
    env["TMOG_COMPILE_CACHE"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    code = (
        "import numpy as np, jax, jax.numpy as jnp\n"
        "import transmogrifai_tpu\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return jnp.tanh(x @ x.T).sum()\n"
        "print(float(f(np.ones((300, 300), np.float32))))\n"
    )
    r1 = subprocess.run([sys.executable, "-c", code], env=env,
                        capture_output=True, text=True, timeout=240)
    assert r1.returncode == 0, r1.stderr[-500:]
    entries = set(os.listdir(tmp_path))
    assert entries, "no cache entries written"
    r2 = subprocess.run([sys.executable, "-c", code], env=env,
                        capture_output=True, text=True, timeout=240)
    assert r2.returncode == 0, r2.stderr[-500:]
    assert r1.stdout == r2.stdout
    # a HIT writes nothing new: same program, same fingerprint — a miss
    # (broken loading) would recompile and add fresh entries
    assert set(os.listdir(tmp_path)) == entries


def test_cache_opt_out(tmp_path):
    env = dict(os.environ)
    env["TMOG_COMPILE_CACHE"] = "0"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    code = (
        "import jax, transmogrifai_tpu\n"
        "print(repr(jax.config.jax_compilation_cache_dir))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-500:]
    out = r.stdout.strip()
    assert out in ("None", "''"), out
