"""XLA kernels (stats/metrics) + GLM model tests."""
import numpy as np
import pytest

from transmogrifai_tpu.ops import metrics_ops as M
from transmogrifai_tpu.ops import stats as S


def test_col_stats_with_nan(rng):
    X = rng.normal(size=(100, 3)).astype(np.float32)
    X[::7, 1] = np.nan
    st = S.col_stats(X)
    ref = X[:, 0]
    assert np.isclose(st.mean[0], ref.mean(), atol=1e-5)
    assert np.isclose(st.variance[0], ref.var(ddof=1), atol=1e-4)
    col1 = X[:, 1][np.isfinite(X[:, 1])]
    assert np.isclose(st.mean[1], col1.mean(), atol=1e-5)
    assert st.count[1] == len(col1)
    assert np.isclose(st.min[0], ref.min()) and np.isclose(st.max[0], ref.max())


def test_col_stats_respects_weights(rng):
    X = rng.normal(size=(50, 2)).astype(np.float32)
    Xpad = np.concatenate([X, np.full((10, 2), 99.0, np.float32)])
    w = np.concatenate([np.ones(50), np.zeros(10)]).astype(np.float32)
    st = S.col_stats(Xpad, w)
    assert np.isclose(st.mean[0], X[:, 0].mean(), atol=1e-5)


def test_pearson_matches_numpy(rng):
    X = rng.normal(size=(200, 4)).astype(np.float32)
    y = (X[:, 0] * 2 + rng.normal(size=200) * 0.5).astype(np.float32)
    corr = np.asarray(S.pearson_with_label(X, y))
    for j in range(4):
        expect = np.corrcoef(X[:, j], y)[0, 1]
        assert np.isclose(corr[j], expect, atol=1e-4)


def test_spearman_monotone(rng):
    x = rng.normal(size=(300,)).astype(np.float32)
    y = np.exp(x)  # monotone but nonlinear
    rho = np.asarray(S.spearman_with_label(x[:, None], y))
    assert rho[0] > 0.999


def test_spearman_ties_match_scipy(rng):
    # discrete columns (post-pivot indicators, small-integer counts) are the
    # common case: average-rank tie handling must match scipy/Spark
    from scipy import stats as sps
    x = rng.integers(0, 4, size=500).astype(np.float32)       # heavy ties
    y = (x + rng.integers(0, 3, size=500)).astype(np.float32)  # ties in label
    rho = float(np.asarray(S.spearman_with_label(x[:, None], y))[0])
    expect = sps.spearmanr(x, y).statistic
    assert np.isclose(rho, expect, atol=1e-5)
    # binary indicator vs binary label, the extreme tie case
    b = (rng.uniform(size=500) < 0.3).astype(np.float32)
    yb = np.where(rng.uniform(size=500) < 0.8, b, 1 - b).astype(np.float32)
    rho_b = float(np.asarray(S.spearman_with_label(b[:, None], yb))[0])
    assert np.isclose(rho_b, sps.spearmanr(b, yb).statistic, atol=1e-5)


def test_stable_sigmoid_extremes():
    from transmogrifai_tpu.models.base import stable_sigmoid
    with np.errstate(over="raise"):  # must not overflow at +-1000
        p = stable_sigmoid(np.array([-1000.0, -20.0, 0.0, 20.0, 1000.0],
                                    np.float32))
    assert p[0] == 0.0 and p[2] == 0.5 and p[4] == 1.0
    assert np.isclose(p[1], np.float32(1 / (1 + np.exp(20.0))))
    assert np.isclose(p[3], np.float32(1 / (1 + np.exp(-20.0))))


def test_contingency_stats_known_values():
    # classic 2x2: perfect association
    t = np.array([[50.0, 0.0], [0.0, 50.0]])
    cs = S.contingency_stats(t)
    assert np.isclose(cs.cramers_v, 1.0, atol=1e-5)
    assert np.isclose(cs.max_rule_confidences[0], 1.0)
    # independence
    t2 = np.array([[25.0, 25.0], [25.0, 25.0]])
    cs2 = S.contingency_stats(t2)
    assert np.isclose(cs2.chi2, 0.0, atol=1e-4)
    assert np.isclose(cs2.mutual_info, 0.0, atol=1e-5)


def test_js_divergence():
    p = np.array([0.5, 0.5, 0.0])
    q = np.array([0.0, 0.5, 0.5])
    d = float(S.js_divergence(p, p))
    assert np.isclose(d, 0.0, atol=1e-6)
    assert 0.0 < float(S.js_divergence(p, q)) <= 1.0


def test_auroc_aupr_vs_sklearn_formula(rng):
    # compare against a simple trusted numpy implementation
    y = (rng.uniform(size=500) < 0.3).astype(np.float32)
    s = np.clip(y * 0.6 + rng.uniform(size=500) * 0.7, 0, 1).astype(np.float32)

    def np_auc(scores, labels):
        order = np.argsort(-scores, kind="stable")
        ys = labels[order]
        ss = scores[order]
        tps = np.cumsum(ys)
        fps = np.cumsum(1 - ys)
        boundary = np.append(ss[1:] != ss[:-1], True)
        tpr = np.concatenate([[0], tps[boundary] / tps[-1]])
        fpr = np.concatenate([[0], fps[boundary] / fps[-1]])
        return np.trapz(tpr, fpr)

    auc = float(M.au_roc(s, y))
    assert np.isclose(auc, np_auc(s, y), atol=1e-5)
    # perfect separation
    assert np.isclose(float(M.au_roc(y, y)), 1.0, atol=1e-6)
    # aupr of perfect = 1, of random ~ base rate
    assert np.isclose(float(M.au_pr(y, y)), 1.0, atol=1e-6)
    rnd = rng.uniform(size=5000).astype(np.float32)
    yy = (rng.uniform(size=5000) < 0.25).astype(np.float32)
    assert abs(float(M.au_pr(rnd, yy)) - 0.25) < 0.05


def test_metrics_ignore_zero_weight_rows(rng):
    y = np.array([1, 0, 1, 0, 1, 1], np.float32)
    s = np.array([.9, .1, .8, .2, .7, .99], np.float32)
    w = np.array([1, 1, 1, 1, 1, 0], np.float32)
    a1 = float(M.au_roc(s[:5], y[:5]))
    a2 = float(M.au_roc(s, y, w))
    assert np.isclose(a1, a2, atol=1e-6)


def test_binary_metrics_confusion():
    y = np.array([1, 1, 0, 0], np.float32)
    s = np.array([0.9, 0.4, 0.6, 0.1], np.float32)
    m = M.binary_metrics(s, y)
    assert (float(m.tp), float(m.fn), float(m.fp), float(m.tn)) == (1, 1, 1, 1)
    assert np.isclose(float(m.error), 0.5)


def test_multiclass_metrics():
    y = np.array([0, 1, 2, 1, 0], np.float32)
    p = np.array([0, 1, 2, 2, 0], np.float32)
    m = M.multiclass_metrics(p, y, 3)
    assert np.isclose(float(m.error), 0.2)
    assert 0.7 < float(m.f1) <= 1.0


def test_regression_metrics():
    y = np.array([1.0, 2.0, 3.0], np.float32)
    p = np.array([1.5, 2.0, 2.5], np.float32)
    m = M.regression_metrics(p, y)
    assert np.isclose(float(m.mae), 1.0 / 3, atol=1e-6)
    assert np.isclose(float(m.mse), (0.25 + 0 + 0.25) / 3, atol=1e-6)
    assert float(m.r2) < 1.0


class TestGLMs:
    def _binary_data(self, rng, n=400, d=5):
        X = rng.normal(size=(n, d)).astype(np.float32)
        beta = np.array([2.0, -1.0, 0.5, 0.0, 0.0], np.float32)
        logits = X @ beta + 0.3
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
        return X, y, beta

    def test_logistic_recovers_signal(self, rng):
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        X, y, beta = self._binary_data(rng)
        model = OpLogisticRegression(reg_param=0.01).fit_arrays(X, y)
        pred, raw, prob = model.predict_arrays(X)
        from transmogrifai_tpu.ops.metrics_ops import au_roc
        assert float(au_roc(prob[:, 1], y)) > 0.85
        assert np.sign(model.beta[0]) > 0 and np.sign(model.beta[1]) < 0

    def test_logistic_l1_sparsifies(self, rng):
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        X, y, _ = self._binary_data(rng)
        m = OpLogisticRegression(reg_param=0.5, elastic_net_param=1.0).fit_arrays(X, y)
        # noise coords should be (near) zeroed
        assert abs(m.beta[3]) < 0.05 and abs(m.beta[4]) < 0.05

    def test_svc(self, rng):
        from transmogrifai_tpu.models.glm import OpLinearSVC
        X, y, _ = self._binary_data(rng)
        m = OpLinearSVC(reg_param=0.01).fit_arrays(X, y)
        pred, raw, prob = m.predict_arrays(X)
        assert prob is None
        # labels are sigmoid-noisy; Bayes accuracy on this draw is ~0.8
        assert (pred == y).mean() > 0.75

    def test_softmax_multiclass(self, rng):
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        n = 600
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = np.argmax(X[:, :3] + 0.3 * rng.normal(size=(n, 3)), axis=1).astype(np.float32)
        m = OpLogisticRegression(reg_param=0.01, max_iter=30).fit_arrays(X, y)
        pred, raw, prob = m.predict_arrays(X)
        assert prob.shape == (n, 3)
        assert (pred == y).mean() > 0.8

    def test_linear_regression_exact(self, rng):
        from transmogrifai_tpu.models.glm import OpLinearRegression
        X = rng.normal(size=(300, 3)).astype(np.float32)
        y = (X @ np.array([1.0, -2.0, 0.5]) + 3.0).astype(np.float32)
        m = OpLinearRegression(reg_param=0.0).fit_arrays(X, y)
        np.testing.assert_allclose(m.beta, [1.0, -2.0, 0.5], atol=1e-2)
        assert np.isclose(m.intercept, 3.0, atol=1e-2)

    def test_glr_poisson(self, rng):
        from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
        X = rng.normal(size=(500, 2)).astype(np.float32)
        rate = np.exp(0.5 * X[:, 0] + 0.2)
        y = rng.poisson(rate).astype(np.float32)
        m = OpGeneralizedLinearRegression(family="poisson").fit_arrays(X, y)
        assert np.isclose(m.beta[0], 0.5, atol=0.1)
        pred, _, _ = m.predict_arrays(X)
        assert (pred >= 0).all()

    def test_naive_bayes(self, rng):
        from transmogrifai_tpu.models.glm import OpNaiveBayes
        n = 400
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        X = rng.poisson(np.where(y[:, None] > 0, [5.0, 1.0], [1.0, 5.0])).astype(np.float32)
        m = OpNaiveBayes().fit_arrays(X, y)
        pred, raw, prob = m.predict_arrays(X)
        assert (pred == y).mean() > 0.9
        assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)

    def test_weighted_fit_ignores_masked_rows(self, rng):
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        X, y, _ = self._binary_data(rng)
        Xpad = np.concatenate([X, rng.normal(size=(50, 5)).astype(np.float32) * 100])
        ypad = np.concatenate([y, np.ones(50, np.float32)])
        w = np.concatenate([np.ones_like(y), np.zeros(50, np.float32)])
        m1 = OpLogisticRegression(reg_param=0.01).fit_arrays(X, y)
        m2 = OpLogisticRegression(reg_param=0.01).fit_arrays(Xpad, ypad, w)
        np.testing.assert_allclose(m1.beta, m2.beta, atol=1e-4)


def test_svc_evaluated_by_margin_not_hard_prediction(rng):
    """Regression: raw-only prediction columns must score by margin."""
    from transmogrifai_tpu.models.glm import OpLinearSVC
    from transmogrifai_tpu.models.prediction import (
        make_prediction_column, positive_score_of, probability_of)
    import numpy as np
    margin = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
    col = make_prediction_column((margin >= 0).astype(np.float32),
                                 raw_prediction=np.stack([-margin, margin], 1))
    assert probability_of(col) is None
    np.testing.assert_allclose(positive_score_of(col), margin)
    # and survives row gathers
    from transmogrifai_tpu.data.dataset import Dataset
    ds = Dataset({"p": col})
    sub = ds.take(np.array([0, 3]))
    np.testing.assert_allclose(positive_score_of(sub.column("p")), [-2.0, 2.0])


def test_onehot_max_pct_cardinality_drops_unique_ids():
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.automl.vectorizers.categorical import OneHotVectorizer
    from transmogrifai_tpu.types import PickList
    ids = [f"id_{i}" for i in range(50)]
    ds = Dataset.from_features([("s", PickList, ids)])
    s = FeatureBuilder.PickList("s").as_predictor()
    model = OneHotVectorizer(min_support=1, max_pct_cardinality=0.5).set_input(s).fit(ds)
    out = model.transform(ds).column(model.output_name())
    # pivot dropped: only OTHER + NULL remain
    assert out.data.shape[1] == 2


def test_spearman_pairwise_complete(rng):
    import numpy as np
    from transmogrifai_tpu.ops import stats as S
    y = rng.normal(size=200).astype(np.float32)
    x = y + 0.1 * rng.normal(size=200).astype(np.float32)
    x_nan = x.copy()
    x_nan[:100] = np.nan  # valid subset is rows 100:
    rho_full = float(S.spearman_with_label(x[100:, None], y[100:])[0])
    rho_masked = float(S.spearman_with_label(x_nan[:, None], y)[0])
    assert np.isclose(rho_full, rho_masked, atol=1e-5)
