"""DecisionTreeNumericMapBucketizer + the date/map/geo/set dsl breadth.

Reference: core/.../impl/feature/DecisionTreeNumericMapBucketizer.scala
(170 LoC) and core/.../dsl/{RichDateFeature, RichMapFeature,
RichLocationFeature, RichVectorFeature}.scala.
"""
from __future__ import annotations

import numpy as np
import pytest

from transmogrifai_tpu.data.dataset import Dataset, column_from_values
from transmogrifai_tpu.testkit.feature_builder import TestFeatureBuilder
from transmogrifai_tpu.transformers.misc import (
    DateToListTransformer, DateToUnitCircleTransformer,
    DecisionTreeNumericMapBucketizer, FilterMapKeys,
)
from transmogrifai_tpu.types import (
    Date, DateTime, Geolocation, RealMap, RealNN,
)


def _map_fixture(n=400, seed=3):
    """k0 predicts the label with a boundary at 0; k1 is noise; k2 is
    missing half the time."""
    rng = np.random.default_rng(seed)
    rows, labels = [], []
    for i in range(n):
        x = float(rng.normal())
        m = {"k0": x, "k1": float(rng.normal())}
        if i % 2 == 0:
            m["k2"] = float(rng.normal())
        rows.append(m)
        labels.append(float(x > 0))
    return TestFeatureBuilder.build(
        ("label", RealNN, labels), ("mp", RealMap, rows), response_index=0)


def test_map_bucketizer_finds_signal_key_splits():
    ds, (label, mp) = _map_fixture()
    est = DecisionTreeNumericMapBucketizer(max_splits=7).set_input(label, mp)
    model = est.fit(ds)
    by_key = dict(zip(model.keys, model.splits_per_key))
    assert set(model.keys) == {"k0", "k1", "k2"}
    assert len(by_key["k0"]) >= 1, "informative key must split"
    assert any(abs(s) < 0.25 for s in by_key["k0"]), \
        f"boundary should be near 0, got {by_key['k0']}"

    out = model.transform(ds)
    col = out.column(model.output_name())
    md_names = [c.grouping for c in col.metadata.columns]
    assert col.data.shape[1] == len(col.metadata.columns)
    assert {"k0", "k1", "k2"} == set(md_names)
    # null indicator for k2 fires on the odd rows
    null_idx = [i for i, c in enumerate(col.metadata.columns)
                if c.grouping == "k2" and c.indicator_value == "NullIndicatorValue"]
    assert len(null_idx) == 1
    assert col.data[1, null_idx[0]] == 1.0
    assert col.data[0, null_idx[0]] == 0.0


def test_map_bucketizer_row_parity_and_roundtrip(tmp_path):
    from transmogrifai_tpu.stages.registry import (
        build_stage, pack_args, unpack_args,
    )
    ds, (label, mp) = _map_fixture(200)
    model = DecisionTreeNumericMapBucketizer().set_input(label, mp).fit(ds)
    col = model.transform(ds).column(model.output_name())
    for i in (0, 1, 7):
        row = {"label": ds.data("label")[i], "mp": ds.data("mp")[i]}
        rv = model.transform_keyvalue(dict(row))
        np.testing.assert_allclose(np.asarray(rv), col.data[i], atol=1e-6)
    store = {}
    packed = pack_args(model.save_args(), store, model.uid)
    rebuilt = build_stage(type(model).__name__, unpack_args(packed, store))
    rebuilt.set_input(label, mp)
    rebuilt.set_output_name(model.output_name())
    np.testing.assert_allclose(
        rebuilt.transform(ds).column(model.output_name()).data, col.data)


def test_filter_map_keys():
    ds, (label, mp) = _map_fixture(50)
    f = FilterMapKeys(block=["k1"]).set_input(mp)
    out = f.transform(ds).column(f.output_name())
    assert all("k1" not in (m or {}) for m in out.data)
    assert f.transform_keyvalue({"mp": {"k0": 1.0, "k1": 2.0}}) == {"k0": 1.0}
    f2 = FilterMapKeys(allow=["k2"]).set_input(mp)
    out2 = f2.transform(ds).column(f2.output_name())
    assert all(set(m or {}) <= {"k2"} for m in out2.data)


# -- dsl breadth --------------------------------------------------------------

def test_dsl_date_ops():
    ms = [1_500_000_000_000 + 3_600_000 * i for i in range(48)]
    ds, (dt,) = TestFeatureBuilder.build(("dt", Date, ms))
    circ = dt.to_unit_circle("HourOfDay")
    stage = circ.origin_stage
    col = stage.transform(ds).column(stage.output_name())
    assert col.data.shape == (48, 2)
    np.testing.assert_allclose((col.data ** 2).sum(axis=1), 1.0, atol=1e-5)
    # 24h later = same point on the circle
    np.testing.assert_allclose(col.data[0], col.data[24], atol=1e-5)

    dl = dt.to_date_list()
    assert dl.type_name == "DateList"
    lst_col = dl.origin_stage.transform(ds).column(dl.name)
    assert lst_col.data[3] == [ms[3]]

    vec = dt.vectorize_dates()
    assert vec.type_name == "OPVector"


def test_dsl_datetime_to_list_narrows():
    ds, (dt,) = TestFeatureBuilder.build(
        ("ts", DateTime, [1_500_000_000_000]))
    assert dt.to_date_list().type_name == "DateTimeList"


def test_dsl_map_and_geo_ops():
    ds, (label, mp) = _map_fixture(80)
    filtered = mp.filter_keys(block=["k1"])
    assert filtered.type_name == "RealMap"
    vec = mp.vectorize_map()
    assert vec.type_name == "OPVector"
    bucketed = mp.autobucketize_map(label, max_splits=3)
    assert bucketed.origin_stage.fit(ds) is not None

    gds, (geo,) = TestFeatureBuilder.build(
        ("loc", Geolocation, [[37.4, -122.1, 5.0], [40.7, -74.0, 3.0]]))
    gvec = geo.vectorize_geo()
    assert gvec.type_name == "OPVector"
    gmodel = gvec.origin_stage.fit(gds)
    assert gmodel.transform(gds).column(gmodel.output_name()).data.shape[0] == 2


def test_dsl_vector_combine_and_descale():
    ds, (a, b) = TestFeatureBuilder.build(
        ("a", RealNN, [1.0, 2.0]), ("b", RealNN, [3.0, 4.0]))
    from transmogrifai_tpu.transformers.misc import ScalerTransformer
    scaler = ScalerTransformer(scaling_type="linear", slope=2.0,
                               intercept=1.0)
    scaled = scaler.set_input(a).get_output()
    descaled = b.descale(scaled, scaler=scaler)
    st = descaled.origin_stage
    sds = scaler.transform(ds)
    out = st.transform(sds).column(st.output_name())
    np.testing.assert_allclose(out.data, [(3.0 - 1.0) / 2.0,
                                          (4.0 - 1.0) / 2.0])

    va = a.vectorize()
    vb = b.vectorize()
    combined = va.combine_with(vb)
    assert combined.type_name == "OPVector"


# -- generic RichFeature + text-extra dsl ops --------------------------------

def test_dsl_generic_feature_ops():
    from transmogrifai_tpu.types import PickList, Real as _Real
    ds, (f,) = TestFeatureBuilder.build(
        ("x", _Real, [1.0, -2.0, None, 4.0]))
    doubled = f.map_values(lambda v: None if v is None else v * 2)
    st = doubled.origin_stage
    out = [st.transform_value(_Real(v)).value for v in (1.0, None)]
    assert out == [2.0, None]

    swapped = f.replace_with(-2.0, 0.0)
    col = swapped.origin_stage.transform(ds).column(swapped.name)
    assert col.data[1] == 0.0 and col.data[0] == 1.0

    pos = f.exists(lambda v: v > 0)
    pcol = pos.origin_stage.transform(ds).column(pos.name)
    assert list(pcol.data[:2]) == [1.0, 0.0]

    clipped = f.filter_values(lambda v: v > 0, default=None)
    ccol = clipped.origin_stage.transform(ds).column(clipped.name)
    assert np.isnan(ccol.data[1])


def test_dsl_email_url_ops():
    from transmogrifai_tpu.types import Email, URL
    ds, (em, url) = TestFeatureBuilder.build(
        ("em", Email, ["jane.doe@example.com", "not-an-email", None]),
        ("url", URL, ["https://sub.example.com/p?q=1", "nope", None]))
    valid = em.is_valid_email()
    vcol = valid.origin_stage.transform(ds).column(valid.name)
    assert list(vcol.data[:2]) == [1.0, 0.0] and np.isnan(vcol.data[2])
    pre = em.email_prefix()
    assert pre.origin_stage.transform(ds).column(pre.name).data[0] == \
        "jane.doe"
    dom = url.url_domain()
    assert dom.origin_stage.transform(ds).column(dom.name).data[0] == \
        "sub.example.com"
    proto = url.url_protocol()
    assert proto.origin_stage.transform(ds).column(proto.name).data[0] == \
        "https"
    ok = url.is_valid_url()
    ocol = ok.origin_stage.transform(ds).column(ok.name)
    assert list(ocol.data[:2]) == [1.0, 0.0]

    from transmogrifai_tpu.types import Text as _Text
    tds, (t,) = TestFeatureBuilder.build(("t", _Text, ["red", None]))
    mpl = t.to_multi_pick_list()
    mcol = mpl.origin_stage.transform(tds).column(mpl.name)
    assert mcol.data[0] == {"red"} and mcol.data[1] == set()


def test_url_parsing_userinfo_and_localhost():
    """One urllib parser everywhere: userinfo/port stripped from domains
    (java.net.URL.getHost semantics), dotless hosts valid."""
    from transmogrifai_tpu.transformers.text import (
        UrlPartsTransformer, ValidUrlTransformer,
    )
    from transmogrifai_tpu.types import URL
    dom = UrlPartsTransformer(part="domain")
    assert dom.transform_value(URL("https://user:pw@example.com/a")).value \
        == "example.com"
    assert dom.transform_value(URL("https://example.com:8443/a")).value \
        == "example.com"
    valid = ValidUrlTransformer()
    assert valid.transform_value(URL("http://localhost:8080/x")).value is True
    assert valid.transform_value(URL("https://user:pw@example.com/")).value \
        is True
    assert valid.transform_value(URL("nope")).value is False


def test_replace_with_array_values():
    import numpy as np
    from transmogrifai_tpu.transformers.misc import ReplaceWithTransformer
    from transmogrifai_tpu.types import OPVector
    t = ReplaceWithTransformer(old_value=np.zeros(2), new_value=np.ones(2))
    t.output_type = OPVector
    out = t.transform_value(OPVector(np.zeros(2)))
    np.testing.assert_array_equal(out.value, np.ones(2))
    out2 = t.transform_value(OPVector(np.array([3.0, 4.0])))
    np.testing.assert_array_equal(out2.value, [3.0, 4.0])
