"""CLI project generator (reference cli module / `op gen`)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu.cli import detect_problem_kind, generate_project


@pytest.fixture()
def titanic_csv(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "titanic.csv"
    lines = ["passengerId,survived,pclass,sex,age,fare"]
    for i in range(120):
        sex = "female" if rng.uniform() < 0.4 else "male"
        age = "" if rng.uniform() < 0.2 else f"{rng.uniform(1, 80):.1f}"
        lines.append(f"{i},{int(rng.uniform() < 0.4)},"
                     f"{rng.integers(1, 4)},{sex},{age},"
                     f"{rng.lognormal(3, 1):.2f}")
    path.write_text("\n".join(lines) + "\n")
    return str(path)


class TestProblemKind:
    def test_kinds(self):
        assert detect_problem_kind([0.0, 1.0, 0.0]) == "binary"
        assert detect_problem_kind([0, 1, 2, 3]) == "multiclass"
        assert detect_problem_kind(list(np.random.uniform(size=50))) \
            == "regression"


class TestGenerate:
    def test_generates_runnable_project(self, titanic_csv, tmp_path):
        out = str(tmp_path / "proj")
        files = generate_project(titanic_csv, response="survived",
                                 output=out, id_col="passengerId",
                                 name="Titanic")
        assert set(files) == {"app.py", "params.json", "README.md"}
        app = (tmp_path / "proj" / "app.py").read_text()
        assert "BinaryClassificationModelSelector" in app
        assert "passengerId" not in app  # id column excluded
        assert "FeatureBuilder.RealNN('survived')" in app \
            or 'FeatureBuilder.RealNN("survived")' in app
        # generated app compiles
        compile(app, "app.py", "exec")

    def test_generated_app_trains(self, titanic_csv, tmp_path):
        out = tmp_path / "proj"
        generate_project(titanic_csv, response="survived", output=str(out),
                         id_col="passengerId")
        env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
        env.pop("PYTHONSTARTUP", None)
        proc = subprocess.run(
            [sys.executable, "app.py", "--run-type", "Train",
             "--model-location", str(tmp_path / "model")],
            cwd=str(out), env=env, capture_output=True, text=True,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert (tmp_path / "model").is_dir()

    def test_bad_response_raises(self, titanic_csv, tmp_path):
        with pytest.raises(ValueError, match="Response column"):
            generate_project(titanic_csv, response="nope",
                             output=str(tmp_path / "p"))
