"""CLI project generator (reference cli module / `op gen`)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu.cli import detect_problem_kind, generate_project


@pytest.fixture()
def titanic_csv(tmp_path):
    rng = np.random.default_rng(0)
    path = tmp_path / "titanic.csv"
    lines = ["passengerId,survived,pclass,sex,age,fare"]
    for i in range(120):
        sex = "female" if rng.uniform() < 0.4 else "male"
        age = "" if rng.uniform() < 0.2 else f"{rng.uniform(1, 80):.1f}"
        lines.append(f"{i},{int(rng.uniform() < 0.4)},"
                     f"{rng.integers(1, 4)},{sex},{age},"
                     f"{rng.lognormal(3, 1):.2f}")
    path.write_text("\n".join(lines) + "\n")
    return str(path)


class TestProblemKind:
    def test_kinds(self):
        assert detect_problem_kind([0.0, 1.0, 0.0]) == "binary"
        assert detect_problem_kind([0, 1, 2, 3]) == "multiclass"
        assert detect_problem_kind(list(np.random.uniform(size=50))) \
            == "regression"


class TestGenerate:
    def test_generates_runnable_project(self, titanic_csv, tmp_path):
        out = str(tmp_path / "proj")
        files = generate_project(titanic_csv, response="survived",
                                 output=out, id_col="passengerId",
                                 name="Titanic")
        assert set(files) == {"features.py", "app.py", "params.json",
                              "test_app.py", "README.md"}
        app = (tmp_path / "proj" / "app.py").read_text()
        feats = (tmp_path / "proj" / "features.py").read_text()
        assert "BinaryClassificationModelSelector" in app
        assert "passengerId" not in feats  # id column excluded
        assert "FeatureBuilder.RealNN('survived')" in feats \
            or 'FeatureBuilder.RealNN("survived")' in feats
        # generated files compile
        compile(app, "app.py", "exec")
        compile(feats, "features.py", "exec")

    def test_generated_app_trains(self, titanic_csv, tmp_path):
        out = tmp_path / "proj"
        generate_project(titanic_csv, response="survived", output=str(out),
                         id_col="passengerId")
        env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
        env.pop("PYTHONSTARTUP", None)
        proc = subprocess.run(
            [sys.executable, "app.py", "--run-type", "Train",
             "--model-location", str(tmp_path / "model")],
            cwd=str(out), env=env, capture_output=True, text=True,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert (tmp_path / "model").is_dir()

    def test_bad_response_raises(self, titanic_csv, tmp_path):
        with pytest.raises(ValueError, match="Response column"):
            generate_project(titanic_csv, response="nope",
                             output=str(tmp_path / "p"))


AVSC = """{
  "type": "record", "name": "Passenger", "fields": [
    {"name": "passengerId", "type": "long"},
    {"name": "survived", "type": "boolean"},
    {"name": "pclass", "type": ["null", "int"]},
    {"name": "sex", "type": {"type": "enum", "name": "Sex",
                             "symbols": ["male", "female"]}},
    {"name": "age", "type": ["null", "double"]},
    {"name": "fare", "type": "double"},
    {"name": "boarded", "type": {"type": "long",
                                 "logicalType": "timestamp-millis"}},
    {"name": "notes", "type": {"type": "map", "values": "string"}}
  ]
}"""


class TestAvroSchema:
    def _write_schema(self, tmp_path, text=AVSC):
        p = tmp_path / "passenger.avsc"
        p.write_text(text)
        return str(p)

    def test_schema_driven_types_and_kind(self, tmp_path):
        """Types come from the Avro schema (AvroField semantics: nullable
        unions, enum -> PickList, logical timestamp -> DateTime,
        unsupported map skipped) and a boolean response makes the kind
        binary with NO data scan (ProblemKind.from)."""
        from transmogrifai_tpu.cli import SchemaSource
        src = SchemaSource.from_avro_schema(self._write_schema(tmp_path))
        by_name = {f.name: f for f in src.fields}
        assert by_name["survived"].feature_type == "Binary"
        assert by_name["pclass"].feature_type == "Integral"
        assert by_name["pclass"].nullable
        assert by_name["sex"].feature_type == "PickList"
        assert by_name["boarded"].feature_type == "DateTime"
        assert "notes" not in by_name  # complex type skipped
        out = str(tmp_path / "proj")
        files = generate_project(response="survived", output=out,
                                 id_col="passengerId",
                                 schema_path=self._write_schema(tmp_path))
        feats = files["features.py"]
        assert "FeatureBuilder.PickList('sex')" in feats
        assert "FeatureBuilder.DateTime('boarded')" in feats
        assert "BinaryClassificationModelSelector" in files["app.py"]
        for fname in ("features.py", "app.py", "test_app.py"):
            compile(files[fname], fname, "exec")

    def test_ambiguous_int_response_requires_kind_or_data(self, tmp_path):
        schema = AVSC.replace('"name": "survived", "type": "boolean"',
                              '"name": "survived", "type": "long"')
        with pytest.raises(ValueError, match="ambiguous"):
            generate_project(response="survived",
                             output=str(tmp_path / "p"),
                             schema_path=self._write_schema(tmp_path, schema))
        files = generate_project(response="survived",
                                 output=str(tmp_path / "p2"),
                                 schema_path=self._write_schema(tmp_path,
                                                                schema),
                                 kind="multiclass")
        assert "MultiClassificationModelSelector" in files["app.py"]

    def test_schema_plus_data_trains(self, titanic_csv, tmp_path):
        """The reference's full flow: Avro schema drives types, CSV test
        data feeds the generated project, and the project TRAINS."""
        schema = """{
          "type": "record", "name": "Titanic", "fields": [
            {"name": "passengerId", "type": "long"},
            {"name": "survived", "type": "boolean"},
            {"name": "pclass", "type": "int"},
            {"name": "sex", "type": "string"},
            {"name": "age", "type": ["null", "double"]},
            {"name": "fare", "type": "double"}
          ]
        }"""
        out = tmp_path / "proj"
        generate_project(input_path=titanic_csv, response="survived",
                         output=str(out), id_col="passengerId",
                         schema_path=self._write_schema(tmp_path, schema))
        env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
        env.pop("PYTHONSTARTUP", None)
        proc = subprocess.run(
            [sys.executable, "app.py", "--run-type", "Train",
             "--model-location", str(tmp_path / "model")],
            cwd=str(out), env=env, capture_output=True, text=True,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert (tmp_path / "model").is_dir()

    def test_response_missing_from_data_raises(self, titanic_csv, tmp_path):
        schema = AVSC.replace('"name": "survived", "type": "boolean"',
                              '"name": "label", "type": "long"')
        with pytest.raises(ValueError, match="no values in the data"):
            generate_project(input_path=titanic_csv, response="label",
                             output=str(tmp_path / "p"),
                             schema_path=self._write_schema(tmp_path, schema))

    def test_schema_only_placeholder_flagged(self, tmp_path):
        files = generate_project(response="survived",
                                 output=str(tmp_path / "p"),
                                 schema_path=self._write_schema(tmp_path))
        assert "PLACEHOLDER" in files["app.py"]
        assert "placeholder" in files["README.md"]
        compile(files["test_app.py"], "test_app.py", "exec")
