"""Unified double-buffered streaming data plane (parallel/tileplane.py).

Covers the pipeline core (fixed-shape re-tiling, bounded host buffer,
error propagation, tile_copy/tile_compute spans + overlap), the four
rewired consumers (stats engine, GLM rounds, tree binning, bulk scoring:
streamed-via-tileplane == resident parity, TMOG_TILEPLANE=0 legacy
parity), the RecompileTracker pins (one tile executable per consumer
shape, 0 recompiles from tile 2 onward), the first-tile Gram-shift
satellite (every row of the source read exactly ONCE even with
corr_matrix), the reader mid-write stability satellite, and the
larger-than-memory contract: an Avro-served fit with X never
materialized and the peak tileplane host buffer <= 2 tiles.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.ops import glm_sweep as GS
from transmogrifai_tpu.ops import stats_engine as SE
from transmogrifai_tpu.ops import trees as T
from transmogrifai_tpu.parallel import tileplane as TP
from transmogrifai_tpu.utils.metrics import collector


@pytest.fixture
def traced():
    collector.enable("test_tileplane")
    try:
        yield collector
    finally:
        collector.finish()
        collector.disable()


def _counting_source(X, y, w, chunk_rows):
    """ArraySource that counts every row handed out — the single-read
    pin: corr_matrix must NOT re-read the first tile."""

    class Counting(TP.ArraySource):
        rows_yielded = 0
        passes = 0

        def chunks(self):
            Counting.passes += 1
            for chunk in super().chunks():
                Counting.rows_yielded += chunk[0].shape[0]
                yield chunk

    return Counting(X, y, w, chunk_rows=chunk_rows)


class TestPipelineCore:
    def test_sum_parity_and_ragged_tail(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(1013, 3)).astype(np.float32)
        w = rng.uniform(0.5, 1.5, size=1013).astype(np.float32)
        src = TP.ArraySource(X, w, chunk_rows=97)

        @jax.jit
        def step(carry, xt, wt):
            return carry + (xt * wt[:, None]).sum(0)

        carry, stats = TP.run_tileplane(
            src, step, jnp.zeros(3, jnp.float32), tile_rows=128,
            label="core")
        np.testing.assert_allclose(np.asarray(carry),
                                   (X * w[:, None]).sum(0), rtol=1e-5)
        assert stats.tiles == -(-1013 // 128)
        assert stats.rows == 1013

    def test_peak_host_buffer_under_two_tiles(self):
        X = np.ones((5000, 4), np.float32)
        src = TP.ArraySource(X, chunk_rows=256)

        @jax.jit
        def step(carry, xt):
            return carry + xt.sum()

        _, stats = TP.run_tileplane(src, step, jnp.zeros((), jnp.float32),
                                    tile_rows=512, label="peak")
        # one tile being assembled + at most one chunk in hand
        assert stats.peak_host_rows <= 2 * 512

    def test_producer_error_propagates(self):
        def factory():
            yield (np.ones((10, 2), np.float32),)
            raise RuntimeError("reader died")

        src = TP.IterSource(factory)

        @jax.jit
        def step(carry, xt):
            return carry + xt.sum()

        with pytest.raises(RuntimeError, match="reader died"):
            TP.run_tileplane(src, step, jnp.zeros((), jnp.float32),
                             tile_rows=8, label="err")

    def test_sink_order_and_valid_rows(self):
        X = np.arange(130, dtype=np.float32).reshape(-1, 1)
        src = TP.ArraySource(X, chunk_rows=40)
        got = []

        @jax.jit
        def step(carry, xt):
            return carry, xt * 2.0

        TP.run_tileplane(src, step, jnp.zeros((), jnp.float32),
                         tile_rows=32, label="sink",
                         sink=lambda t, n: got.append(t[:n]))
        np.testing.assert_allclose(np.concatenate(got), X * 2.0)

    def test_tile_spans_and_overlap(self, traced):
        # compute-heavy step (Gram per 2000x96 tile) so each tile_compute
        # window comfortably contains the producer's next tile_copy
        X = np.random.default_rng(1).normal(
            size=(16000, 96)).astype(np.float32)
        src = TP.ArraySource(X, chunk_rows=2000)

        @jax.jit
        def step(carry, xt):
            g = jnp.matmul(xt.T, xt, preferred_element_type=jnp.float32)
            return carry + jnp.matmul(g, g,
                                      preferred_element_type=jnp.float32)

        with collector.trace_span("pass", kind="span"):
            _, stats = TP.run_tileplane(
                src, step, jnp.zeros((96, 96), jnp.float32),
                tile_rows=2000, label="spans")
        copies = [s for s in collector.trace.spans if s.name == "tile_copy"]
        computes = [s for s in collector.trace.spans
                    if s.name == "tile_compute"]
        assert len(copies) == stats.tiles == 8
        assert len(computes) == 8
        # double buffering: some tile k+1 copy window must intersect an
        # earlier tile's compute window
        overlap = any(
            c.attrs["tile"] > m.attrs["tile"]
            and c.t_start < m.t_end and m.t_start < c.t_end
            for c in copies for m in computes)
        assert overlap, "producer copies never overlapped compute"

    def test_tile_rows_for_env(self, monkeypatch):
        monkeypatch.setenv("TMOG_TILE_MB", "1")
        assert TP.tile_rows_for(1024) == (1 << 20) // 1024
        assert TP.tile_rows_for(4, multiple=3) % 3 == 0

    def test_pipelined_propagates_and_orders(self):
        def gen():
            for i in range(5):
                yield i

        assert list(TP.pipelined(gen(), label="t")) == list(range(5))

        def bad():
            yield 1
            raise ValueError("boom")

        with pytest.raises(ValueError, match="boom"):
            list(TP.pipelined(bad(), label="t"))


class TestStatsConsumer:
    def _data(self, n=3000, d=6, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32) + 50.0
        X[rng.uniform(size=X.shape) < 0.08] = np.nan
        y = rng.integers(0, 2, size=n).astype(np.float32)
        return X, y

    def test_streamed_matches_fused_full_stats(self):
        X, y = self._data()
        lo = np.nanmin(X, 0).astype(np.float32)
        hi = np.nanmax(X, 0).astype(np.float32)
        kw = dict(corr_matrix=True, lo=lo, hi=hi, bins=12,
                  distinct=np.asarray([0.0, 1.0], np.float32))
        fused = SE.run_stats(X, y, **kw)
        streamed = SE.run_stats(X, y, driver="streamed", tile_rows=400,
                                **kw)
        for f in ("count", "mean", "variance", "min", "max", "fill_rate",
                  "corr_label", "num_non_zeros"):
            np.testing.assert_allclose(getattr(streamed, f),
                                       getattr(fused, f), rtol=2e-4,
                                       atol=2e-5, err_msg=f)
        np.testing.assert_allclose(streamed.corr_matrix, fused.corr_matrix,
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(streamed.hist, fused.hist, rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(streamed.contingency, fused.contingency,
                                   rtol=2e-4, atol=1e-4)

    def test_kill_switch_legacy_parity(self, monkeypatch):
        X, y = self._data(seed=3)
        fused = SE.run_stats(X, y, corr_matrix=True)
        monkeypatch.setenv("TMOG_TILEPLANE", "0")
        legacy = SE.run_stats(X, y, corr_matrix=True, driver="streamed",
                              tile_rows=500)
        np.testing.assert_allclose(legacy.mean, fused.mean, rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(legacy.corr_matrix, fused.corr_matrix,
                                   rtol=2e-3, atol=2e-4)

    @pytest.mark.parametrize("tileplane", ["1", "0"])
    def test_single_read_even_with_corr_matrix(self, monkeypatch,
                                               tileplane):
        """The Gram-shift satellite: the first tile's rows flow into the
        pipeline ONCE (the old host pre-pass re-read rows 0:c)."""
        monkeypatch.setenv("TMOG_TILEPLANE", tileplane)
        X, y = self._data(n=2000, seed=5)
        src = _counting_source(X, y, np.ones(2000, np.float32),
                               chunk_rows=250)
        res = SE.run_stats(src, corr_matrix=True, tile_rows=500)
        # one DATA pass + the cached one-chunk shape probe: no row of
        # the first tile flows through the pipeline twice (the old host
        # shift pre-pass re-read rows 0:c)
        assert type(src).passes <= 2
        assert type(src).rows_yielded <= 2000 + 250
        fused = SE.run_stats(X, y, corr_matrix=True)
        np.testing.assert_allclose(res.corr_matrix, fused.corr_matrix,
                                   rtol=2e-3, atol=2e-4)

    def test_one_tile_executable_zero_recompiles_after_tile2(self, traced):
        """RecompileTracker pin: the streamed pass compiles its tile
        program at most twice (shift + merge step) on the FIRST tiles;
        a whole second pass over the same shape books 0 compiles."""
        X, y = self._data(n=2500, d=5, seed=7)
        SE.run_stats(X, y, corr_matrix=True, driver="streamed",
                     tile_rows=500)  # warm: compiles land here
        with collector.trace_span("pinned", kind="span") as sp:
            SE.run_stats(X, y, corr_matrix=True, driver="streamed",
                         tile_rows=500)
        subtree = [s for s in collector.trace.spans
                   if s.span_id == sp.span_id
                   or s.parent_id == sp.span_id]
        assert sum(int(s.attrs.get("compiles", 0)) for s in subtree) == 0

    def test_sharded_tileplane_lane(self):
        from transmogrifai_tpu.parallel.mesh import make_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        X, y = self._data(n=2200, d=5, seed=9)
        fused = SE.run_stats(X, y, corr_matrix=True)
        sh = SE.run_stats(X, y, corr_matrix=True, driver="streamed",
                          mesh=make_mesh(n_batch=2), tile_rows=512)
        np.testing.assert_allclose(sh.mean, fused.mean, rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(sh.corr_matrix, fused.corr_matrix,
                                   rtol=2e-3, atol=2e-4)


class TestGLMConsumer:
    def _problem(self, n=1600, d=5, F=3, seed=11):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        X[:, 1] += 30.0
        beta = rng.normal(size=d)
        y = (X @ beta + 0.2 * rng.normal(size=n)
             > np.median(X @ beta)).astype(np.float32)
        w = np.ones(n, np.float32)
        fold = rng.integers(0, F, size=n)
        masks = np.stack([(fold != k).astype(np.float32)
                          for k in range(F)])
        return X, y, w, masks

    def test_source_rounds_match_device_rounds(self, monkeypatch):
        monkeypatch.setattr(
            "transmogrifai_tpu.parallel.tileplane.tile_rows_for",
            lambda *a, **k: 400)  # force a multi-tile pass
        X, y, w, masks = self._problem()
        regs = np.asarray([0.02, 0.2], np.float32)
        alphas = np.asarray([0.0, 0.5], np.float32)
        B_dev, b0_dev, info_dev = GS.sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, loss="logistic",
            max_iter=25, tol=1e-7, warm_start=False)
        src = TP.ArraySource(X, y, w, masks.T.copy(), chunk_rows=300)
        B_src, b0_src, info_src = GS.sweep_glm_streamed_rounds(
            src, None, None, None, regs, alphas, loss="logistic",
            max_iter=25, tol=1e-7, warm_start=False)
        assert info_src["driver"] == "tileplane"
        assert info_src["glm_rounds"] == info_dev["glm_rounds"]
        np.testing.assert_allclose(B_src, B_dev, rtol=5e-3, atol=5e-4)
        np.testing.assert_allclose(b0_src, b0_dev, rtol=5e-3, atol=5e-4)

    def test_source_warm_start_and_retirement(self):
        X, y, w, masks = self._problem(seed=13)
        regs = np.asarray([0.01, 0.1, 0.5], np.float32)
        alphas = np.zeros(3, np.float32)
        src = TP.ArraySource(X, y, w, masks.T.copy())
        B, b0, info = GS.sweep_glm_streamed_rounds(
            src, None, None, None, regs, alphas, loss="logistic",
            max_iter=30, tol=1e-6, warm_start=True)
        assert info["warm_start"]
        assert info["lanes_retired"] == info["lanes_total"]
        B_dev, _, _ = GS.sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, loss="logistic",
            max_iter=30, tol=1e-6, warm_start=True)
        np.testing.assert_allclose(B, B_dev, rtol=5e-3, atol=7e-4)

    def test_source_kill_switch_sync_parity(self, monkeypatch):
        """TMOG_TILEPLANE=0 must shed the producer thread for the GLM
        source sweep too: run_tileplane degrades to its synchronous
        loop, results unchanged."""
        monkeypatch.setenv("TMOG_TILEPLANE", "0")
        monkeypatch.setattr(
            "transmogrifai_tpu.parallel.tileplane.tile_rows_for",
            lambda *a, **k: 400)
        X, y, w, masks = self._problem(seed=47)
        regs = np.asarray([0.05], np.float32)
        alphas = np.zeros(1, np.float32)
        src = TP.ArraySource(X, y, w, masks.T.copy(), chunk_rows=300)
        B_sync, b0_sync, info = GS.sweep_glm_streamed_rounds(
            src, None, None, None, regs, alphas, loss="logistic",
            max_iter=15, tol=1e-6, warm_start=False)
        monkeypatch.setenv("TMOG_TILEPLANE", "1")
        B_tp, b0_tp, _ = GS.sweep_glm_streamed_rounds(
            src, None, None, None, regs, alphas, loss="logistic",
            max_iter=15, tol=1e-6, warm_start=False)
        np.testing.assert_allclose(B_sync, B_tp, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(b0_sync, b0_tp, rtol=1e-6, atol=1e-7)

    def test_source_round_single_executable(self, monkeypatch):
        monkeypatch.setattr(
            "transmogrifai_tpu.parallel.tileplane.tile_rows_for",
            lambda *a, **k: 397)
        X, y, w, masks = self._problem(n=1200, seed=17)
        src = TP.ArraySource(X, y, w, masks.T.copy(), chunk_rows=397)
        regs = np.asarray([0.05], np.float32)
        alphas = np.zeros(1, np.float32)
        before_step = GS._source_round_step._cache_size()
        GS.sweep_glm_streamed_rounds(src, None, None, None, regs, alphas,
                                     loss="logistic", max_iter=10,
                                     tol=1e-6, warm_start=False)
        grew = GS._source_round_step._cache_size() - before_step
        assert grew <= 1  # ONE executable for every tile of every round

    def test_source_rejects_mesh_and_stray_args(self):
        src = TP.ArraySource(np.ones((8, 2), np.float32),
                             np.ones(8, np.float32),
                             np.ones(8, np.float32),
                             np.ones((8, 2), np.float32))
        with pytest.raises(ValueError, match="ride the source"):
            GS.sweep_glm_streamed_rounds(
                src, np.ones(8), None, None,
                np.asarray([0.1], np.float32),
                np.zeros(1, np.float32), loss="logistic")


class TestTreesConsumer:
    def _X(self, n=4000, d=4, seed=19):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        X[:, 1] *= 40.0
        X[rng.uniform(size=X.shape) < 0.05] = np.nan
        return X

    def test_stream_bin_matrix_exact_parity(self):
        X = self._X()
        edges = np.asarray(T.quantile_edges(jnp.asarray(X), 16))
        resident = np.asarray(T.bin_matrix(jnp.asarray(X),
                                           jnp.asarray(edges)))
        streamed = T.stream_bin_matrix(
            TP.ArraySource(X, chunk_rows=600), edges, tile_rows=640)
        assert streamed.dtype == resident.dtype
        np.testing.assert_array_equal(streamed, resident)

    def test_stream_bin_matrix_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TMOG_TILEPLANE", "0")
        X = self._X(n=900, seed=23)
        edges = np.asarray(T.quantile_edges(jnp.asarray(X), 8))
        resident = np.asarray(T.bin_matrix(jnp.asarray(X),
                                           jnp.asarray(edges)))
        streamed = T.stream_bin_matrix(TP.ArraySource(X, chunk_rows=200),
                                       edges, tile_rows=256)
        np.testing.assert_array_equal(streamed, resident)

    def test_stream_quantile_edges_quality(self):
        X = self._X(n=6000, d=3, seed=29)
        X[:, 2] = 5.0  # constant column
        src = TP.ArraySource(X, chunk_rows=700)
        edges = T.stream_quantile_edges(src, 16, hist_bins=512)
        assert edges.shape == (3, 15)
        for j in range(2):
            col = X[:, j]
            fin = np.isfinite(col)
            true_q = np.quantile(col[fin], np.arange(1, 16) / 16)
            bw = (col[fin].max() - col[fin].min()) / 512
            assert np.abs(edges[j] - true_q).max() < 3 * bw
            assert np.all(np.diff(edges[j]) >= 0)
        assert np.all(edges[2] == 5.0)

    def test_stream_quantile_edges_all_nan_column(self):
        X = self._X(n=800, d=2, seed=31)
        X[:, 1] = np.nan
        edges = T.stream_quantile_edges(TP.ArraySource(X, chunk_rows=200),
                                        8, hist_bins=64)
        assert np.all(np.isnan(edges[1]))
        # all-NaN edges bin every present value to 1 — same as resident
        binned = T.stream_bin_matrix(TP.ArraySource(X, chunk_rows=200),
                                     edges)
        assert set(np.unique(binned[:, 1])) <= {0}

    def test_bin_tile_single_executable(self):
        X = self._X(n=2000, d=3, seed=37)
        edges = np.asarray(T.quantile_edges(jnp.asarray(X), 8))
        before = T._bin_tile_jit._cache_size()
        T.stream_bin_matrix(TP.ArraySource(X, chunk_rows=333), edges,
                            tile_rows=512)
        assert T._bin_tile_jit._cache_size() - before <= 1


class TestScoringConsumer:
    def _model(self):
        from transmogrifai_tpu import FeatureBuilder
        from transmogrifai_tpu.automl import (
            BinaryClassificationModelSelector)
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        from transmogrifai_tpu.readers.readers import ListReader
        from transmogrifai_tpu.stages.params import param_grid
        from transmogrifai_tpu.workflow import Workflow

        rng = np.random.default_rng(41)
        rows = [{"a": float(rng.normal()), "b": float(rng.normal()),
                 "label": 0.0} for _ in range(250)]
        for r in rows:
            r["label"] = float(r["a"] + 0.5 * r["b"] > 0)
        fa = FeatureBuilder.Real("a").extract(
            lambda r: r.get("a")).as_predictor()
        fb = FeatureBuilder.Real("b").extract(
            lambda r: r.get("b")).as_predictor()
        fy = FeatureBuilder.RealNN("label").extract(
            lambda r: r.get("label")).as_response()
        vec = transmogrify([fa, fb])
        pred = BinaryClassificationModelSelector \
            .with_train_validation_split(models_and_parameters=[
                (OpLogisticRegression(), param_grid(reg_param=[0.01]))]) \
            .set_input(fy, vec).get_output()
        model = Workflow().set_reader(ListReader(rows)) \
            .set_result_features(pred).train()
        return model, rows

    def test_tileplane_scores_match_per_record(self):
        from transmogrifai_tpu.readers import (ListStreamingReader,
                                               score_stream)
        model, rows = self._model()
        unlabeled = [{"a": r["a"], "b": r["b"]} for r in rows[:53]]
        tiled = [s for b in score_stream(
            model, ListStreamingReader(unlabeled, batch_size=9),
            tile_rows=16) for s in b]
        fn = model.score_function()
        legacy = [fn(r) for r in unlabeled]
        assert len(tiled) == len(legacy) == 53
        for got, want in zip(tiled, legacy):
            g = list(got.values())[0]
            w = list(want.values())[0]
            assert g["prediction"] == w["prediction"]
            assert g["probability_1"] == pytest.approx(
                w["probability_1"], abs=1e-5)

    def test_kill_switch_restores_per_record_batches(self, monkeypatch):
        from transmogrifai_tpu.readers import (ListStreamingReader,
                                               score_stream)
        monkeypatch.setenv("TMOG_TILEPLANE", "0")
        model, rows = self._model()
        unlabeled = [{"a": r["a"], "b": r["b"]} for r in rows[:20]]
        batches = list(score_stream(
            model, ListStreamingReader(unlabeled, batch_size=7)))
        # legacy semantics: one list per READER batch
        assert [len(b) for b in batches] == [7, 7, 6]

    def test_scoring_zero_recompiles_after_warm_pass(self, traced):
        """RecompileTracker pin for the scoring consumer: fixed record
        tiles mean the workflow's stage programs compile on the first
        tile only — a whole second streamed pass books 0 compiles."""
        from transmogrifai_tpu.readers import (ListStreamingReader,
                                               score_stream)
        model, rows = self._model()
        unlabeled = [{"a": r["a"], "b": r["b"]} for r in rows[:48]]

        def run():
            return list(score_stream(
                model, ListStreamingReader(unlabeled, batch_size=12),
                tile_rows=16))

        run()  # warm: the fixed tile shape compiles here
        n_before = len(collector.trace.spans)
        with collector.trace_span("pinned", kind="span") as sp:
            run()
        fresh = collector.trace.spans[n_before:]
        assert sum(int(s.attrs.get("compiles", 0))
                   for s in fresh + [sp]) == 0

    def test_scoring_tile_spans(self, traced):
        from transmogrifai_tpu.readers import (ListStreamingReader,
                                               score_stream)
        model, rows = self._model()
        unlabeled = [{"a": r["a"], "b": r["b"]} for r in rows[:40]]
        list(score_stream(model, ListStreamingReader(unlabeled,
                                                     batch_size=10),
                          tile_rows=16))
        names = [s.name for s in collector.trace.spans]
        assert names.count("tile_copy") == 3
        assert names.count("tile_compute") == 3


class TestReaderStability:
    def test_midwrite_file_deferred_until_stable(self, tmp_path):
        from transmogrifai_tpu.readers import CSVStreamingReader
        (tmp_path / "done.csv").write_text("x\n1\n2\n")
        partial = tmp_path / "partial.csv"
        partial.write_text("x\n3\n")
        r = CSVStreamingReader(str(tmp_path / "*.csv"))
        # simulate an active writer: partial.csv grows between stats
        sizes = {str(partial): iter([10, 14, 18, 22])}
        real_size = type(r)._size

        def fake_size(self, p):
            it = sizes.get(p)
            return next(it) if it is not None else real_size(self, p)

        r._size = fake_size.__get__(r)
        first = r.poll()
        assert len(first) == 1 and first[0][0]["x"] == 1  # done.csv only
        assert str(partial) in r._pending
        # writer finished: size stable across the next poll
        del sizes[str(partial)]
        partial.write_text("x\n3\n4\n")
        r._pending[str(partial)] = os.path.getsize(str(partial))
        again = r.poll()
        assert len(again) == 1 and [row["x"] for row in again[0]] == [3, 4]
        assert r.poll() == []

    def test_stable_files_yield_first_poll(self, tmp_path):
        from transmogrifai_tpu.readers import CSVStreamingReader
        for i in range(2):
            (tmp_path / f"f{i}.csv").write_text("x\n1\n")
        r = CSVStreamingReader(str(tmp_path / "*.csv"))
        assert len(r.poll()) == 2

    def test_vanished_file_skipped(self, tmp_path):
        from transmogrifai_tpu.readers import CSVStreamingReader
        (tmp_path / "a.csv").write_text("x\n1\n")
        r = CSVStreamingReader(str(tmp_path / "*.csv"))
        r._size = (lambda self, p: -1).__get__(r)
        assert r.poll() == []


class TestAvroEndToEnd:
    """A fit on data served from disk, X never materialized as one
    array: the substrate claim of the whole data plane."""

    def _write_avro(self, path, n=1800, d=4, F=2, seed=43):
        from transmogrifai_tpu.readers.avro import write_avro_file
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        beta = rng.normal(size=d)
        y = (X @ beta > 0).astype(np.float32)
        schema = {"type": "record", "name": "Row", "fields": (
            [{"name": f"x{j}", "type": "float"} for j in range(d)]
            + [{"name": "y", "type": "float"},
               {"name": "id", "type": "long"}])}
        recs = [{**{f"x{j}": float(X[i, j]) for j in range(d)},
                 "y": float(y[i]), "id": i} for i in range(n)]
        write_avro_file(str(path), schema, recs)
        return X, y

    def _sources(self, path, d, F):
        from transmogrifai_tpu.readers.avro import read_avro_file

        def stats_row(r):
            return ([r[f"x{j}"] for j in range(d)], r["y"], 1.0)

        def glm_row(r):
            m = [1.0] * F
            m[r["id"] % F] = 0.0
            return ([r[f"x{j}"] for j in range(d)], r["y"], 1.0, m)

        mk = lambda fn: TP.reader_row_source(  # noqa: E731
            lambda: read_avro_file(str(path)), fn, batch_records=256)
        return mk(stats_row), mk(glm_row)

    def test_avro_fit_never_materializes_x(self, tmp_path):
        d, F = 4, 2
        X, y = self._write_avro(tmp_path / "rows.avro", d=d, F=F)
        stats_src, glm_src = self._sources(tmp_path / "rows.avro", d, F)

        res = SE.run_stats(stats_src, corr_matrix=True, tile_rows=256)
        fused = SE.run_stats(X, y, corr_matrix=True)
        np.testing.assert_allclose(res.mean, fused.mean, rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(res.corr_matrix, fused.corr_matrix,
                                   rtol=2e-3, atol=2e-4)
        ps = SE._last_stream_stats
        # peak tileplane host buffer <= 2 tiles (+ the merged state,
        # which is [d]/[d,d]-shaped — not row-proportional)
        assert ps.peak_host_rows <= 2 * ps.tile_rows
        assert ps.rows == X.shape[0]

        mask = np.stack([(np.arange(X.shape[0]) % F != k)
                         .astype(np.float32) for k in range(F)])
        regs = np.asarray([0.05, 0.2], np.float32)
        alphas = np.zeros(2, np.float32)
        B_src, b0_src, info = GS.sweep_glm_streamed_rounds(
            glm_src, None, None, None, regs, alphas, loss="logistic",
            max_iter=20, tol=1e-6, warm_start=False)
        B_dev, b0_dev, _ = GS.sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y),
            jnp.ones(X.shape[0], jnp.float32), jnp.asarray(mask),
            regs, alphas, loss="logistic", max_iter=20, tol=1e-6,
            warm_start=False)
        assert info["driver"] == "tileplane"
        np.testing.assert_allclose(B_src, B_dev, rtol=5e-3, atol=7e-4)
        np.testing.assert_allclose(b0_src, b0_dev, rtol=5e-3, atol=7e-4)
