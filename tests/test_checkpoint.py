"""Sweep checkpoint/resume (SURVEY §5 failure-recovery subsystem)."""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu.automl.tuning.checkpoint import (
    SweepCheckpoint, sweep_key)
from transmogrifai_tpu.automl.tuning.validators import CrossValidation
from transmogrifai_tpu.evaluators.evaluators import (
    BinaryClassificationEvaluator)
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.stages.params import param_grid


def _data(n=400, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


def test_key_stability_and_sensitivity():
    k1 = sweep_key("M", {"a": 1, "b": 2}, 3, 42, False, "au_pr")
    k2 = sweep_key("M", {"b": 2, "a": 1}, 3, 42, False, "au_pr")
    assert k1 == k2  # order-insensitive
    assert k1 != sweep_key("M", {"a": 1, "b": 3}, 3, 42, False, "au_pr")
    assert k1 != sweep_key("M", {"a": 1, "b": 2}, 5, 42, False, "au_pr")


def test_key_invalidates_on_data_or_base_param_change():
    from transmogrifai_tpu.automl.tuning.checkpoint import data_fingerprint
    X1, y1 = _data(seed=0)
    X2, y2 = _data(seed=1)
    fp1, fp2 = data_fingerprint(X1, y1), data_fingerprint(X2, y2)
    assert fp1 != fp2
    assert fp1 == data_fingerprint(X1.copy(), y1.copy())  # content-stable
    k1 = sweep_key("M", {"a": 1}, 3, 42, False, "au_pr", data_fp=fp1)
    assert k1 != sweep_key("M", {"a": 1}, 3, 42, False, "au_pr", data_fp=fp2)
    assert k1 != sweep_key("M", {"a": 1}, 3, 42, False, "au_pr", data_fp=fp1,
                           base_params={"max_depth": 6})


def test_checkpoint_append_and_reload(tmp_path):
    path = str(tmp_path / "sweep.jsonl")
    c = SweepCheckpoint(path)
    c.record("k1", "M", {"a": 1}, [0.9, 0.8], "au_pr")
    c2 = SweepCheckpoint(path)
    assert c2.get("k1")["fold_metrics"] == [0.9, 0.8]
    # torn tail line is ignored
    with open(path, "a") as f:
        f.write('{"key": "k2", "model_na')
    c3 = SweepCheckpoint(path)
    assert c3.get("k1") is not None and c3.get("k2") is None


def test_resume_skips_finished_cells(tmp_path, monkeypatch):
    X, y = _data()
    path = str(tmp_path / "sweep.jsonl")
    grids = param_grid(max_iter=[3, 5], max_depth=[2])

    cv = CrossValidation(BinaryClassificationEvaluator(), num_folds=2,
                         seed=7)
    cv.checkpoint_path = path
    best1 = cv.validate([(OpGBTClassifier(), grids)], X, y,
                        np.ones_like(y), problem_type="binary")
    assert len(SweepCheckpoint(path)) == 2

    # resume: fits must NOT run again
    calls = {"n": 0}
    orig_fit = OpGBTClassifier.fit_arrays
    orig_mask = OpGBTClassifier.mask_fit_scores

    def spy_fit(self, *a, **k):
        calls["n"] += 1
        return orig_fit(self, *a, **k)

    def spy_mask(self, *a, **k):
        calls["n"] += 1
        return orig_mask(self, *a, **k)
    # GBT sweeps run through the mask-fold path; spy both fit entries
    monkeypatch.setattr(OpGBTClassifier, "fit_arrays", spy_fit)
    monkeypatch.setattr(OpGBTClassifier, "mask_fit_scores", spy_mask)

    cv2 = CrossValidation(BinaryClassificationEvaluator(), num_folds=2,
                          seed=7)
    cv2.checkpoint_path = path
    best2 = cv2.validate([(OpGBTClassifier(), grids)], X, y,
                         np.ones_like(y), problem_type="binary")
    assert calls["n"] == 0  # all cells came from the checkpoint
    assert best2.best_grid == best1.best_grid
    assert best2.best_metric == pytest.approx(best1.best_metric)


def test_different_seed_does_not_reuse(tmp_path, monkeypatch):
    X, y = _data()
    path = str(tmp_path / "sweep.jsonl")
    grids = param_grid(max_iter=[3], max_depth=[2])
    cv = CrossValidation(BinaryClassificationEvaluator(), num_folds=2, seed=7)
    cv.checkpoint_path = path
    cv.validate([(OpGBTClassifier(), grids)], X, y, np.ones_like(y),
                problem_type="binary")

    calls = {"n": 0}
    orig_fit = OpGBTClassifier.fit_arrays
    orig_mask = OpGBTClassifier.mask_fit_scores

    def spy_fit(self, *a, **k):
        calls["n"] += 1
        return orig_fit(self, *a, **k)

    def spy_mask(self, *a, **k):
        calls["n"] += 1
        return orig_mask(self, *a, **k)
    # GBT sweeps run through the mask-fold path; spy both fit entries
    monkeypatch.setattr(OpGBTClassifier, "fit_arrays", spy_fit)
    monkeypatch.setattr(OpGBTClassifier, "mask_fit_scores", spy_mask)
    cv2 = CrossValidation(BinaryClassificationEvaluator(), num_folds=2,
                          seed=8)  # different folds -> stale metrics invalid
    cv2.checkpoint_path = path
    cv2.validate([(OpGBTClassifier(), grids)], X, y, np.ones_like(y),
                 problem_type="binary")
    assert calls["n"] > 0


def test_engine_change_does_not_replay(tmp_path, monkeypatch):
    """Host-native and device tree fits are distinct compute paths (their
    near-tie splits differ): cells recorded under one engine must NOT be
    replayed into a sweep running the other."""
    from transmogrifai_tpu.ops import trees_host as TH
    if not TH.available():
        pytest.skip("native tree builder unavailable")
    X, y = _data()
    path = str(tmp_path / "sweep.jsonl")
    grids = param_grid(max_iter=[3], max_depth=[2])

    cv = CrossValidation(BinaryClassificationEvaluator(), num_folds=2,
                         seed=7)
    cv.checkpoint_path = path
    cv.validate([(OpGBTClassifier(), grids)], X, y, np.ones_like(y),
                problem_type="binary")
    n_host = len(SweepCheckpoint(path))
    assert n_host == 1

    # device engine (host route disabled): the host cells must not match
    monkeypatch.setenv("TMOG_NO_HOST_TREES", "1")
    cv2 = CrossValidation(BinaryClassificationEvaluator(), num_folds=2,
                          seed=7)
    cv2.checkpoint_path = path
    cv2.validate([(OpGBTClassifier(), grids)], X, y, np.ones_like(y),
                 problem_type="binary")
    assert len(SweepCheckpoint(path)) == 2  # a NEW cell was computed
