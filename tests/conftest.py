"""Test config: force an 8-device virtual CPU mesh.

Mirrors the reference's single-local-Spark-session test harness
(utils/.../test/TestSparkContext.scala:46 `master=local[2]`): distribution is
validated on emulated devices, matching how the driver dry-runs the
multi-chip path (xla_force_host_platform_device_count).

NOTE: the environment's sitecustomize imports jax at interpreter startup
with JAX_PLATFORMS=axon (the TPU tunnel), so env vars set here are too late —
we must update the live jax config instead, before any backend initializes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from transmogrifai_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(8)

# the plan-time autotuner (docs/planning.md) must see a COLD corpus in
# tests: tier-1 behavior is pinned to the hand defaults, not to whatever
# measurements this box's bench/calibrate runs have accumulated in the
# user-level cache dir (the planner tests build their own corpora)
import tempfile  # noqa: E402

os.environ["TMOG_PLAN_CORPUS_DIR"] = tempfile.mkdtemp(
    prefix="tmog_test_plan_corpus_")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process bring-up etc.)")
