"""Test config: force an 8-device virtual CPU mesh.

Mirrors the reference's single-local-Spark-session test harness
(utils/.../test/TestSparkContext.scala:46 `master=local[2]`): distribution is
validated on emulated devices, matching how the driver dry-runs the
multi-chip path (xla_force_host_platform_device_count).

NOTE: the environment's sitecustomize imports jax at interpreter startup
with JAX_PLATFORMS=axon (the TPU tunnel), so env vars set here are too late —
we must update the live jax config instead, before any backend initializes.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
