"""Online drift & model-health monitoring (monitor/, docs/monitoring.md).

Unit contracts: the shared-sketch refactor is bit-identical (golden
parity for RawFeatureFilter distributions + alias identity), JS/PSI are
well-defined property-wise (bounds, symmetry, zero-window identity),
reference profiles round-trip through monitor.json, the window sketch
bins BIT-IDENTICALLY to the profile side, tumbling windows roll over on
rows/time/force, and the offline driver produces the same verdict the
serve side would.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.filters import sketches
from transmogrifai_tpu.filters import raw_feature_filter as rff
from transmogrifai_tpu.filters import compute_distributions
from transmogrifai_tpu.monitor import (DriftPolicy, ReferenceProfile,
                                       ServeMonitor, build_profile,
                                       js_divergence_hist,
                                       js_divergence_nats, offline_report,
                                       psi)
from transmogrifai_tpu.monitor.drift import coarsen
from transmogrifai_tpu.monitor.profile import score_hist, score_of
from transmogrifai_tpu.readers.streaming import ListStreamingReader
from transmogrifai_tpu.types import PickList, Real, RealNN, TextMap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- shared-sketch refactor parity -------------------------------------------

#: emitted by filters/sketches.compute_distributions on the dataset below
#: at the time of the refactor out of raw_feature_filter.py — train-time
#: RFF distributions must stay BIT-identical across the move (and after:
#: profile-vs-window comparisons assume both sides bin like this forever)
GOLDEN_DISTS = [
    {"name": "x", "key": None, "count": 12, "nulls": 2,
     "distribution": [3.0, 2.0, 2.0, 0.0, 1.0, 0.0, 0.0, 2.0],
     "summary": [0.0, 10.0, 35.0, 10.0]},
    {"name": "c", "key": None, "count": 12, "nulls": 2,
     "distribution": [1.0, 3.0, 3.0, 3.0, 0.0, 0.0, 0.0, 0.0],
     "summary": [0.0, 0.0, 10.0, 10.0]},
    {"name": "m", "key": "k1", "count": 12, "nulls": 6,
     "distribution": [0.0, 0.0, 0.0, 4.0, 1.0, 0.0, 0.0, 1.0],
     "summary": [0.0, 0.0, 6.0, 6.0]},
    {"name": "m", "key": "k2", "count": 12, "nulls": 8,
     "distribution": [0.0, 3.0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0],
     "summary": [0.0, 0.0, 4.0, 4.0]},
    {"name": "m", "key": "k3", "count": 12, "nulls": 10,
     "distribution": [0.0, 0.0, 0.0, 0.0, 2.0, 0.0, 0.0, 0.0],
     "summary": [0.0, 0.0, 2.0, 2.0]},
    {"name": "m", "key": None, "count": 12, "nulls": 2,
     "distribution": [0.0, 3.0, 1.0, 2.0, 1.0, 1.0, 0.0, 2.0],
     "summary": [0.0, 0.0, 10.0, 10.0]},
]


def _golden_ds():
    vals = [0.0, 0.5, 1.0, 1.5, 2.0, 2.5, 3.0, 5.0, 9.5, 10.0, None,
            float("nan")]
    cats = ["alpha", "beta", "alpha", "gamma", None, "", "delta", "beta",
            "alpha", "beta", "gamma", "x y"]
    maps = [{"k1": "a", "k2": "b"}, {"k1": "c"}, {}, None,
            {"k1": "a", "k3": 3.5}, {"k2": ["l1", "l2"]}, {"k1": "a"},
            {"k2": "b"}, {"k1": "d"}, {"k1": "a"}, {"k2": "b"},
            {"k3": 7.25}]
    return Dataset.from_features([("x", Real, vals), ("c", PickList, cats),
                                  ("m", TextMap, maps)])


class TestSketchRefactorParity:
    def test_golden_distributions_bit_identical(self):
        dists = compute_distributions(_golden_ds(), ["x", "c", "m"], bins=8)
        got = [d.to_json() for d in dists]
        assert got == GOLDEN_DISTS

    def test_rff_aliases_are_the_shared_functions(self):
        # no second implementation may creep back into raw_feature_filter
        assert rff._hash_bin is sketches.hash_bin
        assert rff._is_empty is sketches.is_empty
        assert rff._dist_numeric is sketches.dist_numeric
        assert rff._dist_object is sketches.dist_object
        assert rff._hist_numeric is sketches.hist_numeric
        assert rff._numeric_distributions_batched \
            is sketches.numeric_distributions_batched
        assert rff._map_key_distributions is sketches.map_key_distributions
        assert rff.compute_distributions is sketches.compute_distributions
        assert rff.FeatureDistribution is sketches.FeatureDistribution

    def test_hash_hist_update_matches_legacy_object_rules(self):
        # independent reimplementation of the pre-refactor inline loop
        import zlib

        def legacy(values, bins):
            hist = np.zeros(bins)
            nulls = 0
            for v in values:
                if v is None or (isinstance(v, float) and np.isnan(v)) or \
                        (isinstance(v, (str, list, tuple, set, dict))
                         and len(v) == 0):
                    nulls += 1
                    continue
                items = v if isinstance(v, (list, tuple, set)) else [v]
                if not isinstance(v, (list, tuple, set)):
                    items = [v]
                for item in items:
                    s = item if isinstance(item, str) else repr(item)
                    hist[zlib.crc32(s.encode()) % bins] += 1.0
            return hist, nulls

        values = ["a", "bb", None, "", ["x", "y"], {"k": 1}, float("nan"),
                  ("t1", "t2"), "a", 42]
        want, want_nulls = legacy(values, 16)
        got = np.zeros(16)
        nulls = sum(0 if sketches.hash_hist_update(got, v) else 1
                    for v in values)
        np.testing.assert_array_equal(got, want)
        assert nulls == want_nulls


# -- drift metric properties -------------------------------------------------

class TestDriftMetricProperties:
    @pytest.fixture()
    def hists(self):
        rng = np.random.default_rng(7)
        return [rng.integers(0, 50, size=24).astype(float)
                for _ in range(6)]

    def test_js_bounds_zero_to_ln2(self, hists):
        ln2 = float(np.log(2.0))
        for p in hists:
            for q in hists:
                v = js_divergence_nats(p, q)
                assert 0.0 <= v <= ln2, (v, ln2)
        # disjoint support achieves the upper bound exactly
        p = np.array([1.0, 0.0, 0.0, 0.0])
        q = np.array([0.0, 0.0, 1.0, 1.0])
        assert js_divergence_nats(p, q) == pytest.approx(ln2)
        assert js_divergence_hist(p, q) == pytest.approx(1.0)

    def test_js_symmetry(self, hists):
        for p in hists:
            for q in hists:
                assert js_divergence_nats(p, q) == pytest.approx(
                    js_divergence_nats(q, p), abs=1e-12)

    def test_js_zero_window_identity(self, hists):
        z = np.zeros(24)
        for p in hists:
            assert js_divergence_nats(p, z) == 0.0
            assert js_divergence_nats(z, p) == 0.0
            assert js_divergence_hist(p, z) == 0.0
        assert js_divergence_nats(z, z) == 0.0
        # never NaN, even for garbage (negative mass sums to <= 0)
        assert js_divergence_nats([-1.0, -2.0], [1.0, 2.0]) == 0.0

    def test_js_self_is_zero_and_scale_invariant(self, hists):
        for p in hists:
            assert js_divergence_nats(p, p) == pytest.approx(0.0, abs=1e-12)
            assert js_divergence_nats(p, 7.5 * p) == pytest.approx(
                0.0, abs=1e-9)

    def test_psi_properties(self, hists):
        z = np.zeros(24)
        for p in hists:
            # zero-window identity + self identity + symmetry + sign
            assert psi(p, z) == 0.0
            assert psi(z, p) == 0.0
            assert psi(p, p) == pytest.approx(0.0, abs=1e-12)
            for q in hists:
                v = psi(p, q)
                assert np.isfinite(v) and v >= -1e-12
                assert v == pytest.approx(psi(q, p), abs=1e-9)

    def test_psi_detects_shift(self):
        rng = np.random.default_rng(0)
        a, _ = np.histogram(rng.normal(0, 1, 4000), bins=10, range=(-4, 4))
        b, _ = np.histogram(rng.normal(0, 1, 4000), bins=10, range=(-4, 4))
        c, _ = np.histogram(rng.normal(2, 1, 4000), bins=10, range=(-4, 4))
        assert psi(a, b) < 0.1          # same distribution: stable
        assert psi(a, c) > 0.25         # 2-sigma shift: major

    def test_coarsen_preserves_mass_and_noops_small(self):
        h = np.arange(40, dtype=float)
        c = coarsen(h, 10)
        assert len(c) == 10 and c.sum() == h.sum()
        h2 = np.arange(7, dtype=float)
        np.testing.assert_array_equal(coarsen(h2, 10), h2)

    def test_score_hist_clips_out_of_range(self):
        h = score_hist(np.array([-5.0, 0.5, 2.0, np.nan]), 0.0, 1.0, 4)
        assert h.sum() == 3          # NaN dropped, not binned
        assert h[0] == 1 and h[-1] == 1  # out-of-range mass -> edge bins


# -- profiles ----------------------------------------------------------------

def _make_rows(n=400, seed=3, shift=0.0, cat=("x", "y", "z")):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a = float(rng.normal(shift))
        b = float(rng.normal())
        rows.append({"a": a, "b": b, "c": str(rng.choice(list(cat))),
                     "y": float(a + 0.5 * b > shift)})
    return rows


def _fit_model(rows):
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.workflow import Workflow

    fa = FeatureBuilder.Real("a").extract(lambda r: r.get("a")).as_predictor()
    fb = FeatureBuilder.Real("b").extract(lambda r: r.get("b")).as_predictor()
    fc = FeatureBuilder.PickList("c").extract(
        lambda r: r.get("c")).as_predictor()
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(max_iter=15),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify([fa, fb, fc])).get_output()
    return Workflow().set_reader(ListReader(rows)) \
        .set_result_features(pred).train()


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    rows = _make_rows()
    model = _fit_model(rows)
    mdir = str(tmp_path_factory.mktemp("monitor") / "model")
    model.save(mdir)
    return model, rows, mdir


class TestReferenceProfile:
    def test_saved_next_to_model_and_roundtrips(self, fitted):
        model, rows, mdir = fitted
        assert os.path.exists(os.path.join(mdir, "monitor.json"))
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        doc = load_monitor_profile(mdir)
        prof = ReferenceProfile.from_json(doc)
        assert set(prof.numeric_names) == {"a", "b"}
        assert prof.hashed_names == ["c"]
        assert prof.rows == len(rows)
        a = prof.feature("a")
        assert a.count == len(rows) and a.nulls == 0 and a.lo < a.hi
        assert sum(a.hist) == pytest.approx(len(rows))
        pred = prof.prediction
        assert pred is not None and pred.field == "probability_1"
        assert pred.lo == 0.0 and pred.hi == 1.0
        assert sum(pred.hist) == pytest.approx(len(rows))
        assert 0.0 < pred.mean < 1.0
        # json round trip is lossless
        again = ReferenceProfile.from_json(
            json.loads(json.dumps(prof.to_json())))
        assert again.to_json() == prof.to_json()

    def test_profile_matches_rff_sketch_of_train_data(self, fitted):
        """The profile's numeric histogram IS the RFF sketch of the
        training data — same shared code path, bit-identical."""
        model, rows, _ = fitted
        prof = build_profile(model)
        dists = {d.name: d for d in compute_distributions(
            model._train_data, ["a", "b", "c"], prof.bins) if d.key is None}
        for nm in ("a", "b", "c"):
            assert prof.feature(nm).hist == dists[nm].distribution

    def test_all_missing_feature_excluded(self):
        rows = [{"a": float(i % 5), "dead": None,
                 "y": float(i % 2)} for i in range(300)]
        from transmogrifai_tpu.automl import BinaryClassificationModelSelector
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        from transmogrifai_tpu.models.glm import OpLogisticRegression
        from transmogrifai_tpu.readers.readers import ListReader
        from transmogrifai_tpu.stages.params import param_grid
        from transmogrifai_tpu.workflow import Workflow
        fa = FeatureBuilder.Real("a").extract(
            lambda r: r.get("a")).as_predictor()
        fd = FeatureBuilder.Real("dead").extract(
            lambda r: r.get("dead")).as_predictor()
        fy = FeatureBuilder.RealNN("y").extract(
            lambda r: r.get("y")).as_response()
        pred = BinaryClassificationModelSelector \
            .with_train_validation_split(
                models_and_parameters=[(OpLogisticRegression(),
                                        param_grid(reg_param=[0.01]))],
            ).set_input(fy, transmogrify([fa, fd])).get_output()
        model = Workflow().set_reader(ListReader(rows)) \
            .set_result_features(pred).train()
        prof = build_profile(model)
        assert prof.feature("dead") is None  # no reference to alert on
        assert prof.feature("a") is not None

    def test_kill_switch_skips_profile(self, fitted, tmp_path,
                                       monkeypatch):
        model, _, _ = fitted
        monkeypatch.setenv("TMOG_MONITOR_PROFILE", "0")
        mdir = str(tmp_path / "m2")
        model.save(mdir)
        assert not os.path.exists(os.path.join(mdir, "monitor.json"))

    def test_corrupt_profile_loads_none(self, fitted, tmp_path):
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        d = str(tmp_path)
        with open(os.path.join(d, "monitor.json"), "w") as f:
            f.write("{broken")
        assert load_monitor_profile(d) is None
        assert load_monitor_profile(None) is None


# -- windows -----------------------------------------------------------------

class TestWindowSketch:
    def _profile(self, fitted):
        model, _, mdir = fitted
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        return ReferenceProfile.from_json(load_monitor_profile(mdir))

    def test_window_bins_bit_identical_to_profile(self, fitted):
        """THE alignment pin: replaying the TRAINING rows through the
        window sketch reproduces the profile histograms exactly — same
        hist_bin_ids rule, same pinned edges, zero tolerance."""
        model, rows, _ = fitted
        prof = build_profile(model)
        mon = ServeMonitor(prof, window_rows=10 ** 9,
                           window_seconds=float("inf"))
        X = np.stack([np.asarray([r["a"] for r in rows], np.float32),
                      np.asarray([r["b"] for r in rows], np.float32)],
                     axis=1)
        mon.observe_numeric(X, np.ones(len(rows), np.float32))
        mon.observe_hashed({"c": [r["c"] for r in rows]})
        mon.add_rows(len(rows))
        rep = mon.maybe_rollover(force=True)
        feats = {f["feature"]: f for f in rep["features"]}
        for nm in ("a", "b", "c"):
            assert feats[nm]["js"] == 0.0, (nm, feats[nm])
            assert feats[nm]["psi"] == pytest.approx(0.0, abs=1e-12)
            assert feats[nm]["fill_rate"] == pytest.approx(
                feats[nm]["train_fill_rate"])

    def test_rollover_by_rows_and_alert_latch(self, fitted):
        prof = self._profile(fitted)
        mon = ServeMonitor(prof, window_rows=64,
                           window_seconds=float("inf"))
        rng = np.random.default_rng(0)

        def feed(shift, n):
            X = np.stack([rng.normal(shift, 1, n), rng.normal(0, 1, n)],
                         axis=1).astype(np.float32)
            mon.observe_numeric(X, np.ones(n, np.float32))
            mon.observe_hashed(
                {"c": [str(c) for c in rng.choice(["x", "y", "z"], n)]})
            mon.add_rows(n)

        feed(25.0, 64)  # drifted window
        assert mon.n_windows == 1
        assert mon.alerting and mon.alerts_total > 0
        feed(0.0, 64)   # clean window clears the latch
        assert mon.n_windows == 2
        assert not mon.alerting

    def test_rollover_by_time_and_force(self, fitted):
        prof = self._profile(fitted)
        mon = ServeMonitor(prof, window_rows=10 ** 9, window_seconds=0.0)
        assert mon.maybe_rollover() is None  # empty: timer never fires
        mon.observe_numeric(np.zeros((8, 2), np.float32),
                            np.ones(8, np.float32))
        mon.add_rows(8)  # window_seconds=0: closes immediately
        assert mon.n_windows == 1
        mon2 = ServeMonitor(prof, window_rows=10 ** 9,
                            window_seconds=float("inf"))
        mon2.add_rows(5)
        assert mon2.n_windows == 0
        assert mon2.maybe_rollover(force=True) is not None
        assert mon2.n_windows == 1

    def test_empty_window_reports_no_drift(self, fitted):
        """A window with rows but an EMPTY numeric side (all missing)
        must report 0 JS/PSI (zero-window identity) and flag the fill
        collapse instead."""
        prof = self._profile(fitted)
        mon = ServeMonitor(prof, window_rows=10 ** 9,
                           window_seconds=float("inf"))
        X = np.full((64, 2), np.nan, np.float32)
        mon.observe_numeric(X, np.ones(64, np.float32))
        mon.observe_hashed({"c": [None] * 64})
        mon.add_rows(64)
        rep = mon.maybe_rollover(force=True)
        for f in rep["features"]:
            assert f["js"] == 0.0 and f["psi"] == 0.0
            assert f["fill_rate"] == 0.0
        kinds = {(a["target"], a["metric"]) for a in rep["alerts"]}
        assert ("a", "fill_rate_diff") in kinds
        assert ("a", "fill_ratio") in kinds
        # the infinite fill ratio serializes as null, never NaN: the
        # /drift payload and events.jsonl must stay strict RFC-8259
        # JSON exactly when the worst drift fires
        ratio_alert = next(a for a in rep["alerts"]
                           if a["metric"] == "fill_ratio")
        assert ratio_alert["value"] is None
        json.dumps(rep, allow_nan=False)  # raises on any NaN/inf leak

    def test_min_rows_suppresses_alerts(self, fitted):
        prof = self._profile(fitted)
        mon = ServeMonitor(prof, policy=DriftPolicy(min_rows=100),
                           window_rows=10 ** 9,
                           window_seconds=float("inf"))
        X = np.full((10, 2), 1e6, np.float32)  # absurd drift, tiny window
        mon.observe_numeric(X, np.ones(10, np.float32))
        mon.add_rows(10)
        rep = mon.maybe_rollover(force=True)
        assert rep["alerts"] == []


# -- offline driver ----------------------------------------------------------

class TestOffline:
    def test_quiet_and_drifted_verdicts(self, fitted):
        model, rows, mdir = fitted
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        prof = ReferenceProfile.from_json(load_monitor_profile(mdir))
        same = [{k: v for k, v in r.items() if k != "y"}
                for r in _make_rows(300, seed=11)]
        rep = offline_report(model, ListStreamingReader(same, 128), prof,
                             tile_rows=128)
        assert rep["rows"] == 300 and rep["windows"] == 1
        assert rep["verdict"] == "ok" and rep["alerts_total"] == 0

        shifted = [{"a": v["a"] + 30.0, "b": v["b"], "c": "q"}
                   for v in same]
        rep2 = offline_report(model, ListStreamingReader(shifted, 128),
                              prof, tile_rows=128)
        assert rep2["verdict"] == "drift" and rep2["alerts_total"] > 0
        targets = {a["target"] for a in rep2["last"]["alerts"]}
        assert "a" in targets and "c" in targets

    def test_windowed_offline(self, fitted):
        model, _, mdir = fitted
        from transmogrifai_tpu.workflow.io import load_monitor_profile
        prof = ReferenceProfile.from_json(load_monitor_profile(mdir))
        recs = [{k: v for k, v in r.items() if k != "y"}
                for r in _make_rows(256, seed=5)]
        rep = offline_report(model, ListStreamingReader(recs, 64), prof,
                             tile_rows=64, window_rows=64)
        assert rep["windows"] == 4
        assert rep["rows"] == 256

    @pytest.mark.slow
    def test_monitor_cli_subprocess(self, fitted, tmp_path):
        """`python -m transmogrifai_tpu monitor <model> <csv>`: drifted
        file -> verdict drift + exit 3 under --fail-on-drift; the same
        distribution -> verdict ok, exit 0."""
        import csv
        _, rows, mdir = fitted
        quiet = str(tmp_path / "quiet.csv")
        drifted = str(tmp_path / "drifted.csv")
        for path, shift in ((quiet, 0.0), (drifted, 40.0)):
            with open(path, "w", newline="") as f:
                w = csv.DictWriter(f, fieldnames=["a", "b", "c"])
                w.writeheader()
                for r in _make_rows(300, seed=17):
                    w.writerow({"a": r["a"] + shift, "b": r["b"],
                                "c": r["c"]})
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        env.pop("PYTHONSTARTUP", None)

        def run(path):
            r = subprocess.run(
                [sys.executable, "-m", "transmogrifai_tpu", "monitor",
                 mdir, path, "--fail-on-drift", "--tile-rows", "128"],
                env=env, capture_output=True, text=True, timeout=300)
            assert r.stdout.strip(), r.stderr[-2000:]
            return r.returncode, json.loads(
                r.stdout.strip().splitlines()[-1])

        rc, doc = run(quiet)
        assert rc == 0 and doc["verdict"] == "ok", doc
        rc, doc = run(drifted)
        assert rc == 3 and doc["verdict"] == "drift", doc
        assert doc["alerts_total"] > 0

    def test_score_of_shapes(self):
        assert score_of({"p": {"probability_1": 0.7, "prediction": 1.0}},
                        "p", "probability_1") == 0.7
        assert score_of({"p": {"prediction": 1.0}}, "p",
                        "probability_1") == 1.0  # falls back
        assert score_of({"p": 0.25}, "p", "prediction") == 0.25
        assert score_of({}, "p", "prediction") is None
        assert score_of({"p": "junk"}, "p", "prediction") is None
