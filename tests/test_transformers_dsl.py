"""Math/text/misc transformers + rich dsl syntax.

Mirrors reference suites core/src/test/.../impl/feature/ (MathTransformers,
TextTokenizer, NGram/Jaccard similarity, StringIndexer, CountVectorizer,
ScalerTransformer, DecisionTreeNumericBucketizer...) and the dsl tests.
"""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.testkit import TestFeatureBuilder
from transmogrifai_tpu.types import (
    Binary, Integral, MultiPickList, PickList, Real, RealNN, Text, TextList,
)
from transmogrifai_tpu.workflow import Workflow


def _run(ds, *result_features):
    wf = Workflow().set_input_dataset(ds).set_result_features(*result_features)
    model = wf.train()
    return model.transform(ds)


class TestMath:
    def test_add_and_scalar_ops_through_workflow(self):
        ds, (a, b) = TestFeatureBuilder.build(
            ("a", Real, [1.0, 2.0, None]),
            ("b", Real, [10.0, 20.0, 30.0]))
        s = a + b
        t = a * 2.0
        out = _run(ds, s, t)
        np.testing.assert_allclose(out.column(s.name).data[:2], [11.0, 22.0])
        assert np.isnan(out.column(s.name).data[2])  # empty propagates
        np.testing.assert_allclose(out.column(t.name).data[:2], [2.0, 4.0])

    def test_divide_by_zero_is_empty(self):
        ds, (a, b) = TestFeatureBuilder.build(
            ("a", Real, [1.0, 4.0]), ("b", Real, [2.0, 0.0]))
        q = a / b
        out = _run(ds, q)
        assert out.column(q.name).data[0] == pytest.approx(0.5)
        assert np.isnan(out.column(q.name).data[1])

    def test_unary_chain(self):
        ds, (a,) = TestFeatureBuilder.build(("a", Real, [-4.0, 9.0]))
        r = a.abs().sqrt()
        out = _run(ds, r)
        np.testing.assert_allclose(out.column(r.name).data, [2.0, 3.0])

    def test_log_negative_empty(self):
        ds, (a,) = TestFeatureBuilder.build(("a", Real, [np.e, -1.0]))
        r = a.log()
        out = _run(ds, r)
        assert out.column(r.name).data[0] == pytest.approx(1.0, abs=1e-6)
        assert np.isnan(out.column(r.name).data[1])


class TestTextTransformers:
    def test_tokenize_tf_idf(self):
        docs = ["the cat sat on the mat", "the dog ate the bone",
                "cats and dogs", None]
        ds, (txt,) = TestFeatureBuilder.build(("txt", Text, docs))
        vec = txt.tokenize().tf_idf(vocab_size=16)
        out = _run(ds, vec)
        X = out.column(vec.name).data
        assert X.shape == (4, min(16, X.shape[1]))
        assert np.abs(X[3]).sum() == 0.0  # empty doc -> zero vector

    def test_string_indexer_ranks_by_frequency(self):
        vals = ["b", "a", "b", "b", "a", "c"]
        ds, (txt,) = TestFeatureBuilder.build(("txt", Text, vals))
        idx = txt.index_string()
        out = _run(ds, idx)
        got = out.column(idx.name).data
        assert got[0] == 0.0  # 'b' most frequent
        assert got[5] == 2.0  # 'c' least frequent

    def test_similarity_measures(self):
        from transmogrifai_tpu.transformers.text import (
            JaccardSimilarity, NGramSimilarity)
        sim = NGramSimilarity()
        v = sim.transform_value(TextList(["hello", "world"]),
                                TextList(["hello", "world"]))
        assert v.value == pytest.approx(1.0)
        j = JaccardSimilarity()
        assert j.transform_value(MultiPickList({"a", "b"}),
                                 MultiPickList({"b", "c"})).value \
            == pytest.approx(1 / 3)
        assert j.transform_value(MultiPickList(set()),
                                 MultiPickList(set())).value == 1.0

    def test_lang_mime_phone_email(self):
        from transmogrifai_tpu.transformers.text import (
            EmailToPickList, LangDetector, MimeTypeDetector,
            PhoneNumberParser)
        assert LangDetector().transform_value(
            Text("the cat and the dog is in the house")).value == "en"
        assert LangDetector().transform_value(
            Text("le chat est dans la maison et il est content")).value == "fr"
        import base64
        png = base64.b64encode(b"\x89PNG\r\n\x1a\n123").decode()
        assert MimeTypeDetector().transform_value(Text(png)).value == "image/png"
        assert PhoneNumberParser().transform_value(
            Text("(415) 555-2671")).value is True
        assert PhoneNumberParser().transform_value(Text("123")).value is False
        assert EmailToPickList().transform_value(
            Text("ada@example.com")).value == "example.com"

    def test_text_len(self):
        ds, (txt,) = TestFeatureBuilder.build(("txt", Text, ["abc", None]))
        ln = txt.text_len()
        out = _run(ds, ln)
        assert out.column(ln.name).data[0] == 3.0
        assert out.column(ln.name).data[1] == 0.0


class TestMisc:
    def test_to_occur_and_alias(self):
        ds, (txt,) = TestFeatureBuilder.build(("txt", Text, ["x", None, ""]))
        occ = txt.to_occur()
        out = _run(ds, occ)
        np.testing.assert_allclose(out.column(occ.name).data, [1.0, 0.0, 0.0])

    def test_fill_missing_with_mean(self):
        ds, (a,) = TestFeatureBuilder.build(("a", Real, [1.0, None, 3.0]))
        f = a.fill_missing_with_mean()
        out = _run(ds, f)
        np.testing.assert_allclose(out.column(f.name).data, [1.0, 2.0, 3.0])

    def test_scaler_descaler_round_trip(self):
        from transmogrifai_tpu.transformers.misc import (
            DescalerTransformer, ScalerTransformer)
        sc = ScalerTransformer(scaling_type="linear", slope=2.0,
                               intercept=1.0)
        scaled = sc.transform_value(Real(3.0))
        assert scaled.value == pytest.approx(7.0)
        de = DescalerTransformer(scaler=sc)
        assert de.transform_value(scaled, scaled).value == pytest.approx(3.0)

    def test_percentile_calibrator(self):
        rng = np.random.default_rng(0)
        ds, (s,) = TestFeatureBuilder.build(
            ("s", RealNN, list(rng.uniform(size=1000))))
        cal = s.calibrate_percentile(buckets=100)
        out = _run(ds, cal)
        got = out.column(cal.name).data
        assert got.min() >= 0 and got.max() <= 99
        # roughly uniform bucket occupancy
        assert np.bincount(got.astype(int), minlength=100).std() < 5

    def test_autobucketize_finds_label_cut(self):
        rng = np.random.default_rng(1)
        x = rng.uniform(0, 1, 800)
        label = (x > 0.5).astype(float)
        ds, (fx, fy) = TestFeatureBuilder.build(
            ("x", Real, list(x)), ("label", RealNN, list(label)),
            response_index=1)
        bucketed = fx.autobucketize(fy, max_splits=7)
        out = _run(ds, bucketed)
        X = out.column(bucketed.name).data
        assert X.shape[0] == 800 and X.shape[1] >= 2
        # the learned boundaries must separate the label: rows with x<0.5
        # and x>0.5 never share a bucket
        lo = X[x < 0.45].argmax(axis=1)
        hi = X[x > 0.55].argmax(axis=1)
        assert set(np.unique(lo)).isdisjoint(set(np.unique(hi)))

    def test_drop_indices_by(self):
        from transmogrifai_tpu.automl.transmogrifier import transmogrify
        from transmogrifai_tpu.transformers.misc import DropIndicesByTransformer
        ds, (a, p) = TestFeatureBuilder.build(
            ("a", Real, [1.0, None, 2.0]),
            ("p", PickList, ["x", "y", "x"]))
        vec = transmogrify([a, p])
        wf = Workflow().set_input_dataset(ds).set_result_features(vec)
        model = wf.train()
        out = model.transform(ds)
        col = out.column(vec.name)
        drop = DropIndicesByTransformer(
            predicate=lambda c: c.is_null_indicator)
        dropped = drop.transform_columns(col)
        assert dropped.data.shape[1] < col.data.shape[1]
        assert all(not c.is_null_indicator for c in dropped.metadata.columns)


class TestPersistenceOfTransformers:
    def test_math_chain_save_load(self, tmp_path):
        ds, (a, b) = TestFeatureBuilder.build(
            ("a", Real, [1.0, 2.0, 3.0]), ("b", Real, [4.0, 5.0, 6.0]))
        r = (a + b) * 2.0
        wf = Workflow().set_input_dataset(ds).set_result_features(r)
        model = wf.train()
        path = str(tmp_path / "m")
        model.save(path)
        from transmogrifai_tpu.workflow import WorkflowModel
        loaded = WorkflowModel.load(path)
        out = loaded.transform(ds)
        np.testing.assert_allclose(out.column(r.name).data,
                                   [10.0, 14.0, 18.0])


# -- upgraded light analyzers (round 2: VERDICT weak #6) ---------------------

def test_lang_detector_scripts_and_latin_profiles():
    from transmogrifai_tpu.transformers.text import detect_language
    cases = {
        "The quick brown fox jumps over the lazy dog and runs away": "en",
        "Der schnelle braune Fuchs springt über den faulen Hund und läuft":
            "de",
        "Le renard brun rapide saute par-dessus le chien paresseux dans":
            "fr",
        "El zorro marrón rápido salta sobre el perro perezoso y corre": "es",
        "O rápido cão castanho não salta sobre o cão preguiçoso em": "pt",
        "La volpe marrone veloce salta sopra il cane pigro che è in": "it",
        "Szybki brązowy lis skacze nad leniwym psem i nie jest że": "pl",
        "Hızlı kahverengi tilki tembel köpeğin üzerinden atlar ve bir bu":
            "tr",
        "Быстрая коричневая лиса прыгает через ленивую собаку": "ru",
        "השועל החום המהיר קופץ מעל הכלב העצלן": "he",
        "الثعلب البني السريع يقفز فوق الكلب الكسول": "ar",
        "素早い茶色のキツネは怠け者の犬を飛び越えます": "ja",
        "敏捷的棕色狐狸跳过了懒狗": "zh",
        "빠른 갈색 여우가 게으른 개를 뛰어넘는다": "ko",
        "สุนัขจิ้งจอกสีน้ำตาลกระโดดข้ามสุนัขขี้เกียจ": "th",
        "Γρήγορη καφέ αλεπού πηδά πάνω από το τεμπέλικο σκυλί": "el",
        "तेज भूरी लोमड़ी आलसी कुत्ते के ऊपर कूदती है": "hi",
    }
    for text, want in cases.items():
        assert detect_language(text) == want, (text[:30], want)
    assert detect_language("") is None
    assert detect_language(None) is None


def test_phone_parser_regional_metadata():
    from transmogrifai_tpu.transformers.text import parse_phone
    cases = [
        ("+1 650 253 0000", "US", True), ("(650) 253-0000", "US", True),
        ("1-650-253-0000", "US", True), ("650-253-000", "US", False),
        ("+44 20 7031 3000", "GB", True), ("020 7031 3000", "GB", True),
        ("+49 30 303986300", "DE", True), ("030 303986300", "DE", True),
        ("+33 1 42 68 53 00", "FR", True), ("01 42 68 53 00", "FR", True),
        ("+91 98765 43210", "IN", True), ("098765 43210", "IN", True),
        ("+81 3-6384-9000", "JP", True), ("+86 10 6564 9999", "CN", True),
        ("+55 11 2395-8400", "BR", True), ("12345", "US", False),
        ("+999 123", "US", False), ("++1 650 253 0000", "US", False),
    ]
    for raw, region, want in cases:
        ok, _ = parse_phone(raw, region)
        assert ok == want, (raw, region, want)
    # +cc resolution names the region
    assert parse_phone("+44 20 7031 3000")[1] == "GB"
    assert parse_phone("+49 30 303986300")[1] == "DE"


class TestRound3DslBreadth:
    """New dsl ops closing the gap vs the reference's Rich*Feature files:
    bucketize, z_normalize, to_isotonic_calibrated, is_substring,
    tokenize_regex, remove_stop_words, ngram, tf, drop_indices_by, map."""

    def test_bucketize_fixed_splits(self):
        ds, (x,) = TestFeatureBuilder.build(
            ("x", Real, [1.0, 5.0, 9.0, None]))
        b = x.bucketize(splits=[0.0, 4.0, 8.0, 12.0], track_nulls=True)
        out = _run(ds, b).column(b.name).data
        assert out.shape == (4, 4)  # 3 buckets + null
        assert out[0, 0] == 1.0 and out[1, 1] == 1.0 and out[2, 2] == 1.0
        assert out[3, 3] == 1.0

    def test_z_normalize(self):
        vals = [2.0, 4.0, 6.0, None]
        ds, (x,) = TestFeatureBuilder.build(("x", Real, vals))
        z = x.z_normalize()
        out = _run(ds, z).column(z.name).data
        present = np.array([2.0, 4.0, 6.0])
        # sample std (ddof=1) — Spark StandardScaler semantics
        exp = (present - present.mean()) / present.std(ddof=1)
        np.testing.assert_allclose(out[:3], exp, atol=1e-6)
        assert out[3] == 0.0  # empty -> centered value

    def test_isotonic_calibration_monotone(self):
        rng = np.random.default_rng(0)
        score = rng.uniform(0, 1, 200)
        label = (rng.uniform(size=200) < score).astype(float)
        ds, (y, s) = TestFeatureBuilder.build(
            ("y", RealNN, label.tolist()),
            ("s", RealNN, score.tolist()))
        cal = s.to_isotonic_calibrated(y)
        out = _run(ds, cal).column(cal.name).data
        order = np.argsort(score)
        assert (np.diff(out[order]) >= -1e-9).all()  # non-decreasing

    def test_is_substring(self):
        ds, (a, b) = TestFeatureBuilder.build(
            ("a", Text, ["cat", "dog", None]),
            ("b", Text, ["concatenate", "fish", "x"]))
        r = a.is_substring(b)
        out = _run(ds, r).column(r.name).data
        assert out[0] == 1.0 and out[1] == 0.0
        assert np.isnan(out[2])

    def test_tokenize_regex_and_ngram_and_stopwords(self):
        ds, (t,) = TestFeatureBuilder.build(
            ("t", Text, ["the Cat-sat on  the Mat", None]))
        toks = t.tokenize_regex(pattern=r"[a-z]+")
        kept = toks.remove_stop_words()
        bi = toks.ngram(2)
        out = _run(ds, toks, kept, bi)
        assert list(out.column(toks.name).data[0]) == \
            ["the", "cat", "sat", "on", "the", "mat"]
        assert "the" not in list(out.column(kept.name).data[0])
        assert "the cat" in list(out.column(bi.name).data[0])
        assert list(out.column(toks.name).data[1]) == []

    def test_tf_hashed_counts(self):
        ds, (t,) = TestFeatureBuilder.build(
            ("t", Text, ["a b a", "c"]))
        vec = t.tokenize().tf(num_features=64)
        out = _run(ds, vec).column(vec.name).data
        assert out.shape[1] >= 64
        assert out[0].sum() == 3.0 and out[1].sum() == 1.0

    def test_drop_indices_by(self):
        ds, (p,) = TestFeatureBuilder.build(
            ("p", PickList, ["a", "b", "a"]))
        vec = p.pivot(top_k=5)
        dropped = vec.drop_indices_by(
            lambda c: c.is_null_indicator or c.is_other_indicator)
        out = _run(ds, vec, dropped)
        assert out.column(dropped.name).data.shape[1] < \
            out.column(vec.name).data.shape[1]

    def test_generic_map(self):
        from transmogrifai_tpu.types import Integral as IntegralT, Text as TextT
        ds, (t,) = TestFeatureBuilder.build(
            ("t", Text, ["abc", "de", None]))
        ln = t.map(lambda v: IntegralT(None if v.value is None
                                       else len(v.value) * 10),
                   output_type=IntegralT)
        out = _run(ds, ln).column(ln.name).data
        assert out[0] == 30 and out[1] == 20


def test_map_phone_and_mime_ops():
    """RichMapFeature.isValidPhoneDefaultCountryMap / detectMimeTypes."""
    import base64
    from transmogrifai_tpu.types import Base64Map, TextMap
    pdf = base64.b64encode(b"%PDF-1.4").decode()
    ds, (pm, bm) = TestFeatureBuilder.build(
        ("pm", TextMap, [{"home": "+1 650 253 0000", "junk": "55"}, None]),
        ("bm", Base64Map, [{"doc": pdf}, {}]))
    valid = pm.is_valid_phone_map()
    mimes = bm.detect_mime_types_map()
    out = _run(ds, valid, mimes)
    v0 = out.column(valid.name).data[0]
    assert v0["home"] is True and v0["junk"] is False
    m0 = out.column(mimes.name).data[0]
    assert m0["doc"] == "application/pdf"
    assert out.column(mimes.name).data[1] == {}
