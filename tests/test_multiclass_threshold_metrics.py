"""Multiclass top-N threshold metrics vs a literal port of the reference
algorithm (OpMultiClassificationEvaluator.scala:154 computeMetrics):
per-row stable-descending-sort top-N membership + indexWhere threshold
cutoffs, aggregated with numpy loops. The XLA kernel must agree exactly
(counts are integers)."""
import numpy as np
import pytest

from transmogrifai_tpu.ops.metrics_ops import multiclass_threshold_metrics


def _oracle(probs, labels, top_ns, thresholds):
    """Direct transcription of the reference computeMetrics/treeAggregate."""
    n, C = probs.shape
    T = len(thresholds)
    correct = {t: np.zeros(T, np.int64) for t in top_ns}
    incorrect = {t: np.zeros(T, np.int64) for t in top_ns}
    for i in range(n):
        scores = probs[i]
        label = int(labels[i])
        true_score = scores[label] if 0 <= label < C else 0.0
        # stable sort descending by score (scala sortBy(-_._1))
        order = sorted(range(C), key=lambda j: (-scores[j], j))
        top_score = scores[order[0]]

        def index_where_gt(x):
            for k in range(T):
                if thresholds[k] > x:
                    return k
            return T

        c_true = index_where_gt(true_score)
        c_max = index_where_gt(top_score)
        for t in top_ns:
            in_topn = label in order[:t]
            if in_topn:
                correct[t][0:c_true] += 1
                incorrect[t][c_true:c_max] += 1
            else:
                incorrect[t][0:c_max] += 1
    no_pred = {t: n - correct[t] - incorrect[t] for t in top_ns}
    return correct, incorrect, no_pred


def _check(probs, labels, top_ns=(1, 3), thresholds=None):
    if thresholds is None:
        thresholds = (np.arange(101) / 100.0).astype(np.float32)
    tm = multiclass_threshold_metrics(probs, labels, top_ns=top_ns,
                                      thresholds=thresholds)
    corr, incorr, nopred = _oracle(np.asarray(probs, np.float32),
                                   labels, top_ns, list(thresholds))
    for i, t in enumerate(top_ns):
        np.testing.assert_array_equal(np.asarray(tm.correct_counts[i]),
                                      corr[t], err_msg=f"correct top{t}")
        np.testing.assert_array_equal(np.asarray(tm.incorrect_counts[i]),
                                      incorr[t], err_msg=f"incorrect top{t}")
        np.testing.assert_array_equal(
            np.asarray(tm.no_prediction_counts[i]), nopred[t],
            err_msg=f"no_prediction top{t}")
    # contract from the reference docstring: the three arrays sum to n
    total = (np.asarray(tm.correct_counts) + np.asarray(tm.incorrect_counts)
             + np.asarray(tm.no_prediction_counts))
    assert (total == probs.shape[0]).all()
    return tm


def test_random_probabilities_match_oracle():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(200, 5)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    labels = rng.integers(0, 5, size=200).astype(np.float32)
    _check(probs, labels, top_ns=(1, 2, 3, 10))


def test_ties_match_stable_sort_semantics():
    # equal scores everywhere: top-N membership must follow the original
    # class index order (scala's stable sortBy), not an arbitrary one
    probs = np.full((6, 4), 0.25, np.float32)
    labels = np.array([0, 1, 2, 3, 1, 2], np.float32)
    _check(probs, labels, top_ns=(1, 2, 3))


def test_unseen_label_scores_as_zero():
    # label index beyond the score vector: trueClassScore = 0.0 and the
    # label can never be in the top N (scores.lift semantics)
    probs = np.array([[0.7, 0.3], [0.2, 0.8]], np.float32)
    labels = np.array([5.0, 1.0])
    tm = _check(probs, labels, top_ns=(1, 2))
    # row 0 can never be correct at any threshold
    assert np.asarray(tm.correct_counts)[1].max() == 1  # only row 1


def test_threshold_edges():
    probs = np.array([[1.0, 0.0], [0.5, 0.5], [0.0, 1.0]], np.float32)
    labels = np.array([0.0, 1.0, 0.0])
    _check(probs, labels, top_ns=(1,),
           thresholds=np.array([0.0, 0.5, 1.0], np.float32))


def test_evaluator_surfaces_threshold_metrics():
    from transmogrifai_tpu.evaluators.evaluators import (
        MultiClassificationEvaluator,
    )
    from transmogrifai_tpu.models.prediction import make_prediction_column
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(120, 3)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    labels = rng.integers(0, 3, size=120).astype(np.float32)
    pred = probs.argmax(1).astype(np.float32)
    col = make_prediction_column(pred, logits, probs)
    out = MultiClassificationEvaluator(top_ns=(1, 3)).evaluate_all(
        labels, col)
    tmj = out["threshold_metrics"]
    assert tmj["top_ns"] == [1, 3]
    assert len(tmj["thresholds"]) == 101
    assert set(tmj["correct_counts"]) == {"1", "3"}
    # every cell sums to n
    for t in ("1", "3"):
        tot = (np.array(tmj["correct_counts"][t])
               + np.array(tmj["incorrect_counts"][t])
               + np.array(tmj["no_prediction_counts"][t]))
        assert (tot == 120).all()
    import json
    json.dumps(out)  # summary-JSON serializable end to end
