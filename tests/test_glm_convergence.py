"""Convergence-aware GLM sweep (docs/performance.md "Convergence-aware GLM
sweep"): the squared-loss sufficient-statistics Gram fast path must agree
with the per-lane ops/glm solvers (ridge closed form AND elastic-net
proximal Newton), the IRLS retirement round driver must freeze lanes at
coefficients matching run-to-max_iter within tol, the bucket ladder must
reuse compiled round programs, and the sharded round driver must match the
single-device one on a CPU mesh."""
import copy

import numpy as np
import pytest

import jax.numpy as jnp

from transmogrifai_tpu.automl.tuning import validators as V
from transmogrifai_tpu.automl.tuning.validators import CrossValidation
from transmogrifai_tpu.evaluators.evaluators import Evaluators
from transmogrifai_tpu.models.glm import (
    OpLinearRegression, OpLinearSVC, OpLogisticRegression,
)
from transmogrifai_tpu.ops import glm_sweep as GS
from transmogrifai_tpu.ops.glm import fit_linear, fit_linear_svc, fit_logistic
from transmogrifai_tpu.ops.glm_sweep import (
    bucket_lanes,
    sweep_glm_round,
    sweep_glm_squared_gram,
    sweep_glm_streamed,
    sweep_glm_streamed_rounds,
)


def _binary(n=2000, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.linspace(1.5, -1.5, d)
    p = 1 / (1 + np.exp(-(X @ beta + 0.3)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


def _regression(n=2000, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.linspace(1.0, -1.0, d)
    y = (X @ beta + 0.3 + 0.2 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _masks(y, folds=2, seed=1):
    rng = np.random.default_rng(seed)
    fold = rng.integers(0, folds, size=len(y))
    return np.stack([(fold != k).astype(np.float32) for k in range(folds)])


class TestGramFastPath:
    """(a) Gram fast path vs ops/glm per-lane solvers for ridge and
    elastic-net squared loss."""

    @pytest.mark.parametrize("standardize", [False, True])
    def test_ridge_and_elastic_net_match_per_lane(self, standardize):
        X, y = _regression()
        masks = _masks(y, folds=3)
        w = np.ones_like(y)
        regs = np.array([0.001, 0.05, 0.5], np.float32)
        alphas = np.array([0.0, 0.5, 0.25], np.float32)
        B, b0, _ = sweep_glm_squared_gram(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray(regs), jnp.asarray(alphas),
            50, 1e-6, standardize=standardize)
        B = np.asarray(B)
        b0 = np.asarray(b0)
        # global-weight standardization differs from the per-lane solver's
        # fold-weight standardization at O(1/sqrt(n)) only
        atol = 0.05 if standardize else 3e-3
        for f in range(masks.shape[0]):
            for g in range(len(regs)):
                beta_ref, b0_ref = fit_linear(
                    jnp.asarray(X), jnp.asarray(y),
                    jnp.asarray(masks[f] * w), jnp.asarray(regs[g]),
                    jnp.asarray(alphas[g]), max_iter=50,
                    standardize=standardize)
                assert np.allclose(B[f, g], np.asarray(beta_ref),
                                   atol=atol), (f, g)
                assert abs(b0[f, g] - float(b0_ref)) < atol, (f, g)

    def test_no_intercept(self):
        X, y = _regression(n=1500, d=5, seed=3)
        masks = _masks(y, folds=2, seed=2)
        w = np.ones_like(y)
        B, b0, _ = sweep_glm_squared_gram(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray([0.01], np.float32),
            jnp.asarray([0.25], np.float32), 50, 1e-6,
            fit_intercept=False, standardize=False)
        assert np.allclose(np.asarray(b0), 0.0)
        beta_ref, _ = fit_linear(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(masks[0] * w),
            jnp.asarray(0.01), jnp.asarray(0.25), max_iter=50,
            fit_intercept=False, standardize=False)
        assert np.allclose(np.asarray(B)[0, 0], np.asarray(beta_ref),
                           atol=3e-3)

    def test_nonuniform_weights(self):
        X, y = _regression(n=1800, d=5, seed=7)
        rng = np.random.default_rng(11)
        w = rng.uniform(0.25, 3.0, size=len(y)).astype(np.float32)
        masks = _masks(y, folds=2, seed=5)
        B, b0, _ = sweep_glm_squared_gram(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray([0.05], np.float32),
            jnp.asarray([0.5], np.float32), 50, 1e-6, standardize=False)
        beta_ref, b0_ref = fit_linear(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(masks[1] * w),
            jnp.asarray(0.05), jnp.asarray(0.5), max_iter=50,
            standardize=False)
        assert np.allclose(np.asarray(B)[1, 0], np.asarray(beta_ref),
                           atol=3e-3)
        assert abs(float(b0[1, 0]) - float(b0_ref)) < 3e-3

    def test_single_pass_telemetry(self, monkeypatch):
        """Acceptance gate: a squared-loss sweep through the validator
        executes exactly ONE streaming pass over X for the whole
        fold x grid, asserted via the pass-counter telemetry AND by
        counting Gram-kernel invocations."""
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        calls = []
        orig = GS.sweep_glm_squared_gram

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(GS, "sweep_glm_squared_gram", counting)
        X, y = _regression(n=1500)
        val = CrossValidation(Evaluators.Regression.rmse(), num_folds=3,
                              seed=3)
        best = val.validate(
            [(OpLinearRegression(max_iter=25, standardization=False),
              [{"reg_param": 0.001}, {"reg_param": 0.05},
               {"reg_param": 0.5, "elastic_net_param": 0.5}])],
            X, y, problem_type="regression")
        assert np.isfinite(best.best_metric)
        info = val.last_streamed_telemetry
        assert info is not None and info["kernel"] == "gram"
        assert info["data_passes"] == 1
        assert info["glm_rounds"] == 1
        assert info["lanes_retired"] == info["lanes_total"] == 9
        assert len(calls) == 1  # one kernel dispatch = one X pass
        assert best.validated[0].route == "streamed"


class TestRoundDriver:
    """(b) retirement: a retired lane's coefficients match letting it keep
    iterating, within tol; active-lane counts shrink monotonically."""

    def test_matches_legacy_streamed_logistic(self):
        X, y = _binary()
        masks = _masks(y, folds=2)
        w = np.ones_like(y)
        regs = np.array([0.005, 0.05, 0.3], np.float32)
        alphas = np.array([0.0, 0.25, 0.5], np.float32)
        Bl, b0l = sweep_glm_streamed(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray(regs), jnp.asarray(alphas),
            loss="logistic", max_iter=30, standardize=False)
        Br, b0r, info = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, loss="logistic",
            max_iter=30, tol=1e-6, standardize=False, round_iters=3)
        assert np.allclose(np.asarray(Bl), Br, atol=5e-3)
        assert np.allclose(np.asarray(b0l), b0r, atol=5e-3)
        assert info["lanes_retired"] == info["lanes_total"] == 6
        assert info["data_passes"] == sum(info["iters_per_round"])

    def test_retired_lane_matches_run_to_max_iter(self):
        """Once a lane retires (K=1 rounds force the earliest possible
        retirement), its frozen coefficients match the same lane iterated
        in one uninterrupted round to max_iter — within tol-scale."""
        X, y = _binary(n=1800, d=5, seed=4)
        masks = _masks(y, folds=2, seed=3)
        w = np.ones_like(y)
        regs = np.array([0.002, 0.1, 0.8], np.float32)
        alphas = np.zeros(3, np.float32)
        kw = dict(loss="logistic", max_iter=40, tol=1e-6,
                  standardize=False, warm_start=False)
        B1, b01, i1 = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, round_iters=1, **kw)
        B2, b02, i2 = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, round_iters=40, **kw)
        assert i2["glm_rounds"] == 1
        assert i1["glm_rounds"] > 1
        assert np.allclose(B1, B2, atol=2e-3)
        assert np.allclose(b01, b02, atol=2e-3)
        # monotone shrink of active lanes across the retirement rounds
        act = i1["active_per_round"]
        assert all(a >= b for a, b in zip(act, act[1:]))
        # retirement saved lane-passes vs lock-step-to-the-slowest
        assert i1["lane_passes"] <= i1["lanes_total"] * max(
            sum(i1["iters_per_round"]), 1)

    def test_squared_hinge_matches_per_lane_svc(self):
        X, y = _binary(n=2200, d=6, seed=9)
        masks = _masks(y, folds=2, seed=8)
        w = np.ones_like(y)
        regs = np.array([0.01, 0.2], np.float32)
        B, b0, info = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, np.zeros(2, np.float32),
            loss="squared_hinge", max_iter=30, tol=1e-6,
            standardize=False, round_iters=4)
        for f in range(2):
            for g in range(2):
                beta_ref, b0_ref = fit_linear_svc(
                    jnp.asarray(X), jnp.asarray(y),
                    jnp.asarray(masks[f] * w), jnp.asarray(regs[g]),
                    max_iter=30, standardize=False)
                assert np.allclose(B[f, g], np.asarray(beta_ref),
                                   atol=5e-3), (f, g)
                assert abs(float(b0[f, g]) - float(b0_ref)) < 5e-3

    def test_warm_start_parity_and_telemetry(self):
        """Pathwise warm starts change the iteration path, never the
        answer (convex losses): seeded and unseeded drivers agree within
        tol-scale; the seed round fits only folds x 1 lanes."""
        X, y = _binary(n=1600, d=5, seed=6)
        masks = _masks(y, folds=2, seed=7)
        w = np.ones_like(y)
        regs = np.array([0.001, 0.03, 0.5], np.float32)
        alphas = np.zeros(3, np.float32)
        kw = dict(loss="logistic", max_iter=40, tol=1e-6,
                  standardize=False, round_iters=4)
        Bw, b0w, iw = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, warm_start=True, **kw)
        Bc, b0c, ic = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, warm_start=False, **kw)
        assert iw["warm_start"] and not ic["warm_start"]
        assert iw["active_per_round"][0] == masks.shape[0]  # seed lanes
        assert np.allclose(Bw, Bc, atol=5e-3)
        assert np.allclose(b0w, b0c, atol=5e-3)

    def test_max_iter_caps_every_lane(self):
        X, y = _binary(n=1200, d=4, seed=2)
        masks = _masks(y, folds=2, seed=2)
        w = np.ones_like(y)
        B, b0, info = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), np.asarray([0.01], np.float32),
            np.asarray([0.0], np.float32), loss="logistic", max_iter=1,
            tol=1e-9, standardize=False, round_iters=5)
        assert info["data_passes"] == 1  # one round of exactly one iter
        assert info["lanes_at_cap"] == info["lanes_total"]
        assert np.isfinite(B).all()

    def test_standardize_matches_legacy(self):
        X, y = _binary(n=2400, d=5, seed=12)
        X = X * 2.0 + 1.0
        masks = _masks(y, folds=2, seed=4)
        w = np.ones_like(y)
        Bl, b0l = sweep_glm_streamed(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray([0.02], np.float32),
            jnp.asarray([0.0], np.float32), loss="logistic", max_iter=30,
            standardize=True)
        Br, b0r, _ = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), np.asarray([0.02], np.float32),
            np.asarray([0.0], np.float32), loss="logistic", max_iter=30,
            tol=1e-6, standardize=True, round_iters=4)
        assert np.allclose(np.asarray(Bl), Br, atol=5e-3)
        assert np.allclose(np.asarray(b0l), b0r, atol=5e-3)


class TestBucketLadder:
    """(c) compaction pads to a power-of-two ladder and reuses compiled
    round programs across rounds and sweeps."""

    def test_bucket_lanes_ladder(self):
        assert bucket_lanes(1) == GS._BUCKET_MIN
        assert bucket_lanes(GS._BUCKET_MIN) == GS._BUCKET_MIN
        assert bucket_lanes(9) == 16
        assert bucket_lanes(17) == 32
        assert bucket_lanes(240) == 256

    def test_round_program_cache_reuse(self):
        """Two sweeps with different lane counts in the SAME bucket (and
        every round of each) share one compiled round program, asserted
        via the jit cache size."""
        if not hasattr(sweep_glm_round, "_cache_size"):
            pytest.skip("jit cache introspection unavailable")
        X, y = _binary(n=900, d=4, seed=5)
        masks = _masks(y, folds=2, seed=6)
        w = np.ones_like(y)

        def run(n_grid):
            regs = np.linspace(0.01, 0.5, n_grid).astype(np.float32)
            return sweep_glm_streamed_rounds(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
                jnp.asarray(masks), regs, np.zeros(n_grid, np.float32),
                loss="logistic", max_iter=25, tol=1e-6,
                standardize=False, round_iters=2, warm_start=False)

        before = sweep_glm_round._cache_size()
        _, _, i1 = run(5)   # 10 lanes -> bucket 16, several rounds
        after_first = sweep_glm_round._cache_size()
        assert after_first - before <= 2  # ladder may shrink 16 -> 8
        _, _, i2 = run(8)   # 16 lanes -> same 16-bucket programs
        assert sweep_glm_round._cache_size() == after_first
        for info in (i1, i2):
            assert all(b in (8, 16) for b in info["bucket_sizes"])
            assert all(b & (b - 1) == 0 for b in info["bucket_sizes"])

    def test_traced_tol_max_iter_share_executable(self):
        """Satellite: tol/max_iter are traced scalars on the legacy
        streamed kernel too — retuning them must NOT recompile."""
        if not hasattr(sweep_glm_streamed, "_cache_size"):
            pytest.skip("jit cache introspection unavailable")
        X, y = _binary(n=700, d=4, seed=8)
        masks = _masks(y, folds=2, seed=9)
        w = np.ones_like(y)

        def run(mi, tl):
            return sweep_glm_streamed(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
                jnp.asarray(masks), jnp.asarray([0.05], np.float32),
                jnp.asarray([0.0], np.float32), loss="logistic",
                max_iter=mi, tol=tl, standardize=False)

        run(10, 1e-5)
        size_after_first = sweep_glm_streamed._cache_size()
        run(17, 1e-4)
        run(23, 1e-7)
        assert sweep_glm_streamed._cache_size() == size_after_first


class TestRoundCheckpoint:
    """Round-granular persistence: resume at the last retirement boundary
    reproduces the uninterrupted run bit for bit."""

    def test_driver_state_resume_bit_identical(self):
        X, y = _binary(n=1400, d=5, seed=10)
        masks = _masks(y, folds=2, seed=11)
        w = np.ones_like(y)
        regs = np.array([0.005, 0.08, 0.4], np.float32)
        alphas = np.zeros(3, np.float32)
        kw = dict(loss="logistic", max_iter=30, tol=1e-6,
                  standardize=False, round_iters=2, warm_start=True)
        snapshots = []
        B_full, b0_full, info_full = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas,
            on_round=lambda st: snapshots.append(copy.deepcopy(st)), **kw)
        assert len(snapshots) == info_full["glm_rounds"]
        # resume from the state after the SECOND round boundary
        B_res, b0_res, info_res = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas,
            state=copy.deepcopy(snapshots[1]), **kw)
        assert np.array_equal(B_full, B_res)
        assert np.array_equal(b0_full, b0_res)
        assert info_res["glm_rounds"] == info_full["glm_rounds"]

    def test_roundcheckpoint_file_roundtrip(self, tmp_path):
        from transmogrifai_tpu.automl.tuning.checkpoint import (
            RoundCheckpoint)
        rc = RoundCheckpoint(str(tmp_path / "sweep.jsonl.glm_rounds.npz"))
        st = GS._new_round_state(6, 4)
        st["B"][:] = 1.5
        st["rounds"] = 2
        st["active_per_round"] = [6, 3]
        st["warmed"] = True
        rc.save("k1", st)
        assert rc.load("other-key") is None  # mismatched key ignored
        got = rc.load("k1")
        assert got is not None
        assert np.array_equal(got["B"], st["B"])
        assert got["rounds"] == 2 and got["warmed"] is True
        assert got["active_per_round"] == [6, 3]
        rc.clear()
        assert rc.load("k1") is None

    def test_validator_round_checkpoint_resume(self, monkeypatch, tmp_path):
        """A streamed sweep killed mid-rounds resumes at the last
        retirement boundary: the resumed run executes FEWER rounds and
        lands on the same winner as a clean run."""
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        X, y = _binary(n=1400)
        ev = Evaluators.BinaryClassification.au_pr()
        grids = [{"reg_param": 0.002}, {"reg_param": 0.05},
                 {"reg_param": 0.4}]
        est = lambda: OpLogisticRegression(max_iter=30)

        class _Boom(RuntimeError):
            pass

        orig = GS.sweep_glm_streamed_rounds
        seen_states = []

        def dying(*a, **k):
            inner = k.get("on_round")

            def bomb(st):
                if inner is not None:
                    inner(st)
                seen_states.append(copy.deepcopy(st))
                if st["rounds"] >= 2:
                    raise _Boom()
            k["on_round"] = bomb
            return orig(*a, **k)

        monkeypatch.setattr(GS, "sweep_glm_streamed_rounds", dying)
        val = CrossValidation(ev, num_folds=2, seed=5)
        val.checkpoint_path = str(tmp_path / "ck.jsonl")
        with pytest.raises(_Boom):
            val.validate([(est(), [dict(g) for g in grids])], X, y)
        interrupted_rounds = seen_states[-1]["rounds"]
        # resume: the round file must exist and seed the next attempt
        resumed = []

        def resuming(*a, **k):
            # snapshot NOW: the driver mutates the state dict in place
            resumed.append(copy.deepcopy(k.get("state")))
            return orig(*a, **k)

        monkeypatch.setattr(GS, "sweep_glm_streamed_rounds", resuming)
        val2 = CrossValidation(ev, num_folds=2, seed=5)
        val2.checkpoint_path = val.checkpoint_path
        b2 = val2.validate([(est(), [dict(g) for g in grids])], X, y)
        assert resumed and resumed[0] is not None
        assert resumed[0]["rounds"] == interrupted_rounds
        # clean reference run
        val3 = CrossValidation(ev, num_folds=2, seed=5)
        b3 = val3.validate([(est(), [dict(g) for g in grids])], X, y)
        assert b2.best_grid == b3.best_grid
        for a, b in zip(b2.validated, b3.validated):
            assert np.allclose(a.fold_metrics, b.fold_metrics, atol=5e-3)


class TestShardedRounds:
    """(d) sharded round driver / Gram path match single-device on a
    2-device CPU mesh."""

    def _mesh(self):
        from transmogrifai_tpu.parallel.mesh import make_mesh
        return make_mesh(n_batch=2, n_model=1)

    def _put(self, mesh, X, y, w, masks):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        row = NamedSharding(mesh, P("batch", None))
        vec = NamedSharding(mesh, P("batch"))
        mrow = NamedSharding(mesh, P(None, "batch"))
        return (jax.device_put(X, row), jax.device_put(y, vec),
                jax.device_put(w, vec), jax.device_put(masks, mrow))

    def test_sharded_round_driver_matches_single(self):
        mesh = self._mesh()
        n = 2048  # multiple of the 2-way batch axis
        X, y = _binary(n=n, d=5, seed=14)
        w = np.ones_like(y)
        masks = _masks(y, folds=2, seed=13)
        regs = np.array([0.01, 0.2], np.float32)
        alphas = np.array([0.0, 0.5], np.float32)
        kw = dict(loss="logistic", max_iter=25, tol=1e-6,
                  standardize=True, round_iters=3)
        B1, b01, i1 = sweep_glm_streamed_rounds(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), regs, alphas, **kw)
        Xd, yd, wd, md = self._put(mesh, X, y, w, masks)
        B2, b02, i2 = sweep_glm_streamed_rounds(
            Xd, yd, wd, md, regs, alphas, mesh=mesh, **kw)
        assert np.allclose(B1, B2, atol=3e-3)
        assert np.allclose(b01, b02, atol=3e-3)
        assert i1["lanes_retired"] == i2["lanes_retired"]

    def test_sharded_gram_matches_single(self):
        import jax
        mesh = self._mesh()
        X, y = _regression(n=2048, d=5, seed=15)
        X = X * 2.0 + 3.0  # exercise the psum'd standardization too
        w = np.ones_like(y)
        masks = _masks(y, folds=2, seed=15)
        regs = np.array([0.01, 0.3], np.float32)
        alphas = np.array([0.0, 0.5], np.float32)
        B1, b01, _ = sweep_glm_squared_gram(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(masks), jnp.asarray(regs), jnp.asarray(alphas),
            50, 1e-6, standardize=True)
        Xd, yd, wd, md = self._put(mesh, X, y, w, masks)
        B2, b02, _ = GS.sweep_glm_squared_gram_sharded(
            mesh, Xd, yd, wd, md, jnp.asarray(regs), jnp.asarray(alphas),
            50, 1e-6, standardize=True)
        assert np.allclose(np.asarray(B1), np.asarray(B2), atol=3e-3)
        assert np.allclose(np.asarray(b01), np.asarray(b02), atol=3e-3)

    def test_validator_mesh_routes_match(self, monkeypatch):
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        mesh = self._mesh()
        X, y = _regression(n=1000, d=5, seed=16)  # odd n: pads
        ev = Evaluators.Regression.rmse()
        grids = [{"reg_param": 0.001}, {"reg_param": 0.1}]
        vm = CrossValidation(ev, num_folds=2, seed=3, mesh=mesh)
        bm = vm.validate([(OpLinearRegression(max_iter=25), grids)], X, y,
                         problem_type="regression")
        assert vm.last_streamed_telemetry["kernel"] == "gram"
        vp = CrossValidation(ev, num_folds=2, seed=3)
        bp = vp.validate([(OpLinearRegression(max_iter=25), grids)], X, y,
                         problem_type="regression")
        assert bm.best_grid == bp.best_grid
        for a, b in zip(bp.validated, bm.validated):
            assert np.allclose(a.fold_metrics, b.fold_metrics, atol=5e-3)


class TestValidatorRouting:
    def test_logistic_routes_rounds_and_matches_vmapped(self, monkeypatch):
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        X, y = _binary(n=1800)
        ev = Evaluators.BinaryClassification.au_pr()
        grids = [{"reg_param": 0.001}, {"reg_param": 0.05},
                 {"reg_param": 0.5}]
        vs = CrossValidation(ev, num_folds=3, seed=7)
        bs = vs.validate([(OpLogisticRegression(max_iter=20),
                           [dict(g) for g in grids])], X, y)
        info = vs.last_streamed_telemetry
        assert info["kernel"] == "rounds"
        assert info["lanes_total"] == 9
        assert sum(info["iters_per_round"]) == info["data_passes"]
        # monotone active-lane shrink over the post-seed rounds
        act = info["active_per_round"][1:] if info.get("warm_start") \
            else info["active_per_round"]
        assert all(a >= b for a, b in zip(act, act[1:]))
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 10**12)
        vv = CrossValidation(ev, num_folds=3, seed=7)
        bv = vv.validate([(OpLogisticRegression(max_iter=20),
                           [dict(g) for g in grids])], X, y)
        assert bs.best_grid == bv.best_grid
        for a, b in zip(bv.validated, bs.validated):
            assert np.allclose(a.fold_metrics, b.fold_metrics, atol=5e-3)

    def test_kill_switches_fall_back_to_legacy(self, monkeypatch):
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        monkeypatch.setenv("TMOG_GLM_ROUNDS", "0")
        monkeypatch.setenv("TMOG_GLM_GRAM", "0")
        X, y = _binary(n=900)
        ev = Evaluators.BinaryClassification.au_pr()
        val = CrossValidation(ev, num_folds=2, seed=2)
        best = val.validate([(OpLogisticRegression(max_iter=15),
                              [{"reg_param": 0.01}])], X, y)
        assert np.isfinite(best.best_metric)
        assert val.last_streamed_telemetry["kernel"] == "global"
        Xr, yr = _regression(n=900)
        valr = CrossValidation(Evaluators.Regression.rmse(), num_folds=2,
                               seed=2)
        bestr = valr.validate([(OpLinearRegression(max_iter=15),
                                [{"reg_param": 0.01}])], Xr, yr,
                              problem_type="regression")
        assert np.isfinite(bestr.best_metric)
        assert valr.last_streamed_telemetry["kernel"] == "global"

    def test_svc_routes_rounds(self, monkeypatch):
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        X, y = _binary(n=1200)
        ev = Evaluators.BinaryClassification.au_roc()
        val = CrossValidation(ev, num_folds=2, seed=3)
        best = val.validate([(OpLinearSVC(max_iter=15),
                              [{"reg_param": 0.01}, {"reg_param": 0.1}])],
                            X, y)
        assert np.isfinite(best.best_metric)
        assert val.last_streamed_telemetry["kernel"] == "rounds"

    def test_collector_records_sweep_convergence(self, monkeypatch):
        from transmogrifai_tpu.utils.metrics import collector
        monkeypatch.setattr(V, "STREAMED_SWEEP_MIN_ROWS", 0)
        X, y = _binary(n=900)
        collector.enable("test_sweep_conv")
        try:
            val = CrossValidation(
                Evaluators.BinaryClassification.au_pr(), num_folds=2,
                seed=4)
            val.validate([(OpLogisticRegression(max_iter=15),
                           [{"reg_param": 0.01}, {"reg_param": 0.2}])],
                         X, y)
            recs = collector.current.sweep_metrics
            assert recs and recs[-1].kernel == "rounds"
            assert recs[-1].lanes_total == 4
            out = collector.current.to_json()
            assert "sweep_metrics" in out
        finally:
            collector.disable()


class TestBenchFlopModel:
    """Satellite: the stale streamed FLOP model (compressed-triangle 2nT,
    hard-coded 15 iterations) is gone — executed FLOPs come from the
    sweep's measured lane-passes."""

    def test_streamed_model_uses_measured_lane_passes(self):
        import bench
        cfg = dict(n_rows=1000, n_cols=8, glm_grid=4, folds=2)
        n, d = 1000, 8
        per_lane_pass = 4 * n * d + 2 * n * d * d
        got = bench.glm_flops_estimate(cfg, "streamed",
                                       {"lane_passes": 7})
        assert got == per_lane_pass * 7
        # executed work (the padded bucket) outranks the logical count
        got_pad = bench.glm_flops_estimate(
            cfg, "streamed", {"lane_passes": 7, "padded_lane_passes": 16})
        assert got_pad == per_lane_pass * 16
        # fallback without telemetry: 15 iterations x all lanes, but on
        # the FULL symmetric einsum model (not the retired triangle)
        got_fb = bench.glm_flops_estimate(cfg, "streamed", None)
        assert got_fb == per_lane_pass * 15 * 4 * 2
        T = d * (d + 1) // 2
        stale = (4 * n * d + 2 * n * T) * 15 * 4 * 2
        assert got_fb != stale

    def test_vmapped_model_unchanged(self):
        import bench
        cfg = dict(n_rows=500, n_cols=4, glm_grid=3, folds=2)
        n, d = 500, 4
        per_iter_lane = 4 * n * d + 2 * n * d * d + n * d
        assert bench.glm_flops_estimate(cfg, "vmapped") == \
            per_iter_lane * 15 * 6
