"""Tests for splitters, validators, and the ModelSelector sweep.

Mirrors reference suites core/src/test/.../impl/tuning/{DataBalancerTest,
DataCutterTest,OpCrossValidationTest}.scala and
.../impl/selector/ModelSelectorTest.scala.
"""
import numpy as np
import pytest

from transmogrifai_tpu.automl import (
    BinaryClassificationModelSelector, CrossValidation, DataBalancer,
    DataCutter, DataSplitter, MultiClassificationModelSelector,
    RegressionModelSelector, TrainValidationSplit,
)
from transmogrifai_tpu.automl.selector import ModelSelector
from transmogrifai_tpu.data.dataset import column_from_values
from transmogrifai_tpu.evaluators.evaluators import Evaluators
from transmogrifai_tpu.models.glm import (
    OpLinearRegression, OpLinearSVC, OpLogisticRegression, OpNaiveBayes,
)
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.types import OPVector, RealNN


def _binary_data(rng, n=400, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.linspace(1.0, -1.0, d).astype(np.float32)
    p = 1 / (1 + np.exp(-(X @ beta)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


# -- splitters --------------------------------------------------------------

def test_splitter_holdout_fractions(rng):
    sp = DataSplitter(seed=1, reserve_test_fraction=0.2)
    tr, te = sp.split(1000)
    assert len(tr) == 800 and len(te) == 200
    assert len(np.intersect1d(tr, te)) == 0
    assert len(np.union1d(tr, te)) == 1000


def test_data_balancer_downsamples_majority(rng):
    y = np.concatenate([np.ones(50), np.zeros(5000)]).astype(np.float32)
    b = DataBalancer(seed=7, sample_fraction=0.1)
    prep = b.prepare(y)
    yb = y[prep.indices]
    frac = yb.sum() / len(yb)
    assert abs(frac - 0.1) < 0.02
    assert not prep.summary["already_balanced"]


def test_data_balancer_balanced_passthrough(rng):
    y = (rng.uniform(size=1000) < 0.4).astype(np.float32)
    b = DataBalancer(seed=7, sample_fraction=0.1)
    prep = b.prepare(y)
    assert prep.summary["already_balanced"]
    assert len(prep.indices) == 1000


def test_data_balancer_caps_max_training_sample():
    y = np.concatenate([np.ones(500), np.zeros(5000)]).astype(np.float32)
    b = DataBalancer(seed=7, sample_fraction=0.2, max_training_sample=2000)
    prep = b.prepare(y)
    assert len(prep.indices) <= 2100
    yb = y[prep.indices]
    assert abs(yb.sum() / len(yb) - 0.2) < 0.05


def test_data_cutter_drops_rare_labels(rng):
    y = np.array([0.0] * 500 + [1.0] * 450 + [2.0] * 3).astype(np.float32)
    c = DataCutter(seed=1, min_label_fraction=0.01)
    prep = c.prepare(y)
    assert prep.summary["labels_dropped"] == [2.0]
    assert set(np.unique(y[prep.indices])) == {0.0, 1.0}
    assert prep.label_map == {0: 0, 1: 1}


def test_data_cutter_max_categories(rng):
    y = rng.integers(0, 20, size=2000).astype(np.float32)
    c = DataCutter(seed=1, max_label_categories=5)
    prep = c.prepare(y)
    assert len(np.unique(y[prep.indices])) == 5


# -- validators -------------------------------------------------------------

def test_cv_fold_masks_partition(rng):
    y = (rng.uniform(size=100) < 0.5).astype(np.float32)
    cv = CrossValidation(Evaluators.BinaryClassification.au_pr(), num_folds=4)
    masks = cv.fold_masks(y)
    assert masks.shape == (4, 100)
    # every row is in validation exactly once
    assert np.allclose((1 - masks).sum(axis=0), 1.0)


def test_cv_stratified_fold_masks(rng):
    y = np.concatenate([np.ones(30), np.zeros(90)]).astype(np.float32)
    cv = CrossValidation(Evaluators.BinaryClassification.au_pr(),
                         num_folds=3, stratify=True)
    masks = cv.fold_masks(y)
    for f in range(3):
        val = masks[f] == 0
        assert y[val].sum() == 10  # positives spread evenly


def test_cv_vmapped_matches_sequential(rng):
    """The vmapped GLM sweep must rank grids like the per-fold loop."""
    X, y = _binary_data(rng)
    grids = param_grid(reg_param=[0.01, 0.1], elastic_net_param=[0.0])
    ev = Evaluators.BinaryClassification.au_roc()
    cv = CrossValidation(ev, num_folds=3, seed=5)
    est = OpLogisticRegression(max_iter=25)

    best_v = cv.validate([(est, grids)], X, y, problem_type="binary")
    vmapped = {tuple(sorted(v.grid.items())): v.mean_metric
               for v in best_v.validated}

    seq = cv._validate_sequential(est, grids, X, y,
                                  np.ones_like(y), cv.fold_masks(y))
    seqd = {tuple(sorted(v.grid.items())): v.mean_metric for v in seq}
    for k in vmapped:
        assert abs(vmapped[k] - seqd[k]) < 0.02, (k, vmapped[k], seqd[k])


def test_multiclass_cv_vmapped_matches_sequential(rng):
    """The softmax sweep runs as ONE XLA program (no host fold loops) and
    ranks grids like the sequential per-fold path."""
    n, d, k = 600, 4, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    centers = rng.normal(size=(k, d)).astype(np.float32) * 2.5
    y = rng.integers(0, k, size=n).astype(np.float32)
    X += centers[y.astype(int)]
    grids = param_grid(reg_param=[0.001, 0.3], elastic_net_param=[0.0])
    ev = Evaluators.MultiClassification.error()
    cv = CrossValidation(ev, num_folds=3, seed=11)
    est = OpLogisticRegression(max_iter=30)

    assert cv._vmappable(est, grids, "multiclass")
    best = cv.validate([(est, grids)], X, y, problem_type="multiclass")
    vmapped = {tuple(sorted(v.grid.items())): v.mean_metric
               for v in best.validated}

    seq = cv._validate_sequential(est, grids, X, y,
                                  np.ones_like(y), cv.fold_masks(y))
    seqd = {tuple(sorted(v.grid.items())): v.mean_metric for v in seq}
    for key in vmapped:
        assert abs(vmapped[key] - seqd[key]) < 0.02, (
            key, vmapped[key], seqd[key])
    # same winner either way
    best_seq = min(seqd, key=seqd.get)
    assert tuple(sorted(best.best_grid.items())) == best_seq


def test_cv_picks_better_model(rng):
    X, y = _binary_data(rng)
    ev = Evaluators.BinaryClassification.au_roc()
    cv = CrossValidation(ev, num_folds=3, seed=5)
    lr = OpLogisticRegression(max_iter=25)
    # absurd L1 zeroes every coefficient -> constant scores -> AuROC 0.5
    best = cv.validate(
        [(lr, param_grid(reg_param=[0.01, 1000.0],
                         elastic_net_param=[1.0]))], X, y,
        problem_type="binary")
    assert best.best_grid["reg_param"] == 0.01
    assert best.best_metric > 0.8


def test_train_validation_split(rng):
    X, y = _binary_data(rng)
    ev = Evaluators.BinaryClassification.au_roc()
    tvs = TrainValidationSplit(ev, train_ratio=0.75, seed=5)
    masks = tvs.fold_masks(y)
    assert masks.shape[0] == 1
    frac_val = (masks[0] == 0).mean()
    assert 0.2 < frac_val < 0.3
    best = tvs.validate([(OpLogisticRegression(max_iter=25),
                          param_grid(reg_param=[0.01]))], X, y,
                        problem_type="binary")
    assert best.best_metric > 0.8


def test_validator_mixed_vmapped_and_sequential(rng):
    X, y = _binary_data(rng)
    ev = Evaluators.BinaryClassification.au_roc()
    cv = CrossValidation(ev, num_folds=2, seed=3)
    best = cv.validate(
        [(OpLogisticRegression(max_iter=25), param_grid(reg_param=[0.01])),
         (OpNaiveBayes(), [dict()])],
        X, y, problem_type="binary")
    assert best.name in ("OpLogisticRegression", "OpNaiveBayes")
    assert len(best.validated) == 2


# -- model selector ---------------------------------------------------------

def test_binary_selector_end_to_end(rng):
    X, y = _binary_data(rng, n=600)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, seed=11,
        model_types=("OpLogisticRegression", "OpLinearSVC"))
    model = sel.fit_arrays(X, y)
    s = model.summary
    assert s.best_model_name in ("OpLogisticRegression", "OpLinearSVC")
    # 4*2 LR grids + 4 SVC grids
    assert len(s.validation_results) == 12
    assert s.holdout_evaluation["au_roc"] > 0.75
    assert "au_pr" in s.train_evaluation
    pred, raw, prob = model.predict_arrays(X)
    assert pred.shape == (600,)
    assert set(np.unique(pred)) <= {0.0, 1.0}
    assert "Selected:" in s.pretty()


def test_multiclass_selector(rng):
    n, d = 900, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    centers = rng.normal(size=(3, d)).astype(np.float32) * 3
    y = rng.integers(0, 3, size=n).astype(np.float32)
    X += centers[y.astype(int)]
    sel = MultiClassificationModelSelector.with_cross_validation(
        num_folds=2, seed=3, model_types=("OpLogisticRegression",))
    model = sel.fit_arrays(X, y)
    assert model.summary.problem_type == "multiclass"
    pred, _, prob = model.predict_arrays(X)
    acc = (pred == y).mean()
    assert acc > 0.8
    assert prob.shape == (n, 3)


def test_regression_selector(rng):
    n, d = 500, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    y = X @ beta + 0.1 * rng.normal(size=n).astype(np.float32)
    sel = RegressionModelSelector.with_train_validation_split(
        seed=3, model_types=("OpLinearRegression",))
    model = sel.fit_arrays(X, y.astype(np.float32))
    assert model.summary.holdout_evaluation["rmse"] < 0.3
    pred, _, _ = model.predict_arrays(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.99


def test_selector_fit_columns_path(rng):
    X, y = _binary_data(rng, n=200)
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, model_types=("OpLogisticRegression",))
    label_col = column_from_values(RealNN, [float(v) for v in y])
    vec_col = column_from_values(OPVector, [list(map(float, r)) for r in X])
    model = sel.fit_columns(label_col, vec_col)
    assert model.summary.best_model_name == "OpLogisticRegression"
