"""Binary threshold curves (reference OpBinaryClassificationEvaluator
numBins=100 threshold metrics) — vectorized implementation vs a brute-force
per-threshold confusion computation."""
import numpy as np

from transmogrifai_tpu.evaluators.evaluators import (
    BinaryClassificationEvaluator, Evaluators,
)
from transmogrifai_tpu.models.prediction import make_prediction_column


def _pred_col(scores):
    scores = np.asarray(scores, np.float32)
    prob = np.stack([1 - scores, scores], axis=1)
    pred = (scores >= 0.5).astype(np.float32)
    raw = np.log(np.clip(prob, 1e-9, None))
    return make_prediction_column(pred, raw, prob)


def _brute(scores, y, w, thresholds):
    out = []
    for t in thresholds:
        pos = scores >= t
        tp = (w * pos * y).sum()
        fp = (w * pos * (1 - y)).sum()
        fn = (w * ~pos * y).sum()
        prec = tp / max(tp + fp, 1e-12)
        rec = tp / max(tp + fn, 1e-12)
        out.append((prec, rec))
    return np.array(out)


class TestThresholdCurves:
    def test_matches_brute_force(self):
        rng = np.random.default_rng(0)
        n = 2000
        y = (rng.uniform(size=n) < 0.4).astype(np.float64)
        scores = np.clip(0.4 * y + rng.uniform(size=n) * 0.6, 0, 1)
        w = rng.uniform(0.5, 2.0, size=n)
        ev = BinaryClassificationEvaluator()
        col = _pred_col(scores)
        curves = ev.threshold_curves(y, col, w, num_bins=50)
        thr = np.array(curves["thresholds"])
        # the Prediction column stores f32 scores — brute-force on the
        # same rounded values the evaluator actually sees
        brute = _brute(scores.astype(np.float32).astype(np.float64), y, w,
                       thr)
        assert np.allclose(curves["precision_by_threshold"], brute[:, 0],
                           atol=1e-9)
        assert np.allclose(curves["recall_by_threshold"], brute[:, 1],
                           atol=1e-9)

    def test_recall_monotone_and_endpoints(self):
        rng = np.random.default_rng(1)
        y = (rng.uniform(size=500) < 0.5).astype(np.float64)
        scores = rng.uniform(size=500)
        ev = BinaryClassificationEvaluator()
        curves = ev.threshold_curves(y, _pred_col(scores), None)
        rec = np.array(curves["recall_by_threshold"])
        # thresholds descend => predicted-positive set grows => recall
        # non-decreasing, ending at 1 (lowest threshold = min score)
        assert (np.diff(rec) >= -1e-12).all()
        assert abs(rec[-1] - 1.0) < 1e-9

    def test_curves_included_in_evaluate_all_but_not_summary_floats(self):
        rng = np.random.default_rng(2)
        y = (rng.uniform(size=300) < 0.5).astype(np.float64)
        scores = rng.uniform(size=300)
        ev = Evaluators.BinaryClassification.au_pr()
        out = ev.evaluate_all(y, _pred_col(scores))
        assert len(out["thresholds"]) == 100
        assert {"au_pr", "au_roc", "precision", "recall"} <= set(out)
        # scalar metrics stay floats (selector summaries filter on that)
        assert isinstance(out["au_pr"], float)

    def test_constant_scores_degenerate(self):
        y = np.array([0.0, 1.0, 1.0, 0.0])
        scores = np.full(4, 0.7)
        ev = BinaryClassificationEvaluator()
        curves = ev.threshold_curves(y, _pred_col(scores), None, num_bins=10)
        # every threshold equals the constant score: all rows positive
        assert np.allclose(curves["recall_by_threshold"], 1.0)
        assert np.allclose(curves["precision_by_threshold"], 0.5)
