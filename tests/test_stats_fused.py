"""One-pass statistics engine (ops/stats_engine.py): parity vs the legacy
per-call reductions, driver equivalence (fused / sharded / streamed),
SanityChecker + RawFeatureFilter + RecordInsightsCorr rewires, and the
tracing-based pin that a pearson-mode fit makes exactly ONE device pass.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from transmogrifai_tpu.ops import stats as S
from transmogrifai_tpu.ops import stats_engine as SE
from transmogrifai_tpu.utils.metrics import collector


def _data(seed=0, n=512, d=6, nan_frac=0.15, classes=3):
    """Shared shape across tests so the engine's jit cache is hit."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    if nan_frac:
        X[rng.uniform(size=(n, d)) < nan_frac] = np.nan
    y = rng.integers(0, classes, size=n).astype(np.float32)
    return X, y, rng


def _truth_corr(X, y, w=None):
    """f64 pairwise-complete weighted Pearson ground truth."""
    n, d = X.shape
    w = np.ones(n) if w is None else np.asarray(w, np.float64)
    out = np.zeros(d)
    for j in range(d):
        ok = np.isfinite(X[:, j])
        xv = X[ok, j].astype(np.float64)
        yv = y[ok].astype(np.float64)
        wv = w[ok]
        cw = wv.sum()
        if cw <= 0:
            out[j] = 0.0
            continue
        mx = (wv * xv).sum() / cw
        my = (wv * yv).sum() / cw
        cov = (wv * (xv - mx) * (yv - my)).sum()
        den = np.sqrt((wv * (xv - mx) ** 2).sum()
                      * (wv * (yv - my) ** 2).sum())
        out[j] = cov / den if den > 0 else 0.0
    return out


class TestEngineParity:
    def test_col_stats_match_legacy(self):
        X, y, _ = _data()
        st = SE.run_stats(X, y)
        cs = S.col_stats(jnp.asarray(X))
        np.testing.assert_allclose(st.count, np.asarray(cs.count))
        np.testing.assert_allclose(st.mean, np.asarray(cs.mean),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st.variance, np.asarray(cs.variance),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(st.min, np.asarray(cs.min))
        np.testing.assert_allclose(st.max, np.asarray(cs.max))
        np.testing.assert_allclose(st.num_non_zeros,
                                   np.asarray(cs.num_non_zeros))
        np.testing.assert_allclose(
            st.fill_rate,
            np.asarray(S.fill_rate(jnp.asarray(X))), rtol=1e-6, atol=1e-7)

    def test_corr_label_matches_legacy_and_truth(self):
        X, y, _ = _data(seed=1)
        st = SE.run_stats(X, y)
        legacy = np.asarray(S.pearson_with_label(jnp.asarray(X),
                                                 jnp.asarray(y)))
        np.testing.assert_allclose(st.corr_label, legacy,
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(st.corr_label, _truth_corr(X, y),
                                   atol=1e-4)

    def test_weighted_corr_matches_f64_truth(self):
        # the engine is w-LINEAR (a true weighted correlation); the legacy
        # kernel folds w into both centered factors (w^2 weighting), so
        # the oracle here is the f64 truth, not the legacy kernel
        X, y, rng = _data(seed=2)
        w = rng.choice([0.5, 1.0, 2.0], size=len(y)).astype(np.float32)
        st = SE.run_stats(X, y, w)
        np.testing.assert_allclose(st.corr_label, _truth_corr(X, y, w),
                                   atol=1e-4)

    def test_large_mean_welford_stability(self):
        # mean ~1e6, unit variance: the one-pass E[x^2]-mean^2 form loses
        # EVERYTHING in f32 (legacy col_stats reports ~1e5x the true
        # variance here); the tile-merged Welford engine stays exact
        X, y, _ = _data(seed=3, nan_frac=0.0)
        X[:, 0] += 1e6
        st = SE.run_stats(X, y)
        true_var = X[:, 0].astype(np.float64).var(ddof=1)
        fused_err = abs(st.variance[0] - true_var) / true_var
        # ~0.3% — the floor set by f32 tile sums of 1e6-mean data; the
        # legacy one-pass form is off by ORDERS OF MAGNITUDE here
        assert fused_err < 1e-2
        legacy_var = float(np.asarray(
            S.col_stats(jnp.asarray(X)).variance)[0])
        legacy_err = abs(legacy_var - true_var) / true_var
        assert legacy_err > 100 * max(fused_err, 1e-6)
        np.testing.assert_allclose(st.corr_label, _truth_corr(X, y),
                                   atol=1e-3)

    def test_corr_matrix_matches_legacy(self):
        X, y, _ = _data(seed=4)
        st = SE.run_stats(X, y, corr_matrix=True)
        legacy = np.asarray(S.pearson_matrix(jnp.asarray(X)))
        np.testing.assert_allclose(st.corr_matrix, legacy,
                                   rtol=1e-3, atol=2e-4)
        np.testing.assert_allclose(np.diag(st.corr_matrix),
                                   np.ones(X.shape[1]), atol=1e-5)

    def test_contingency_matches_legacy(self):
        X, y, _ = _data(seed=5, nan_frac=0.05)
        G = (X[:, :3] > 0).astype(np.float32)
        X2 = np.concatenate([G * 3.0, X[:, 3:]], axis=1)  # multi-hot-ish
        distinct = np.unique(y)
        clip = np.array([True, True, True, False, False, False])
        st = SE.run_stats(X2, y, distinct=distinct, clip=clip)
        Y = np.zeros((len(y), len(distinct)), np.float32)
        for j, v in enumerate(distinct):
            Y[y == v, j] = 1.0
        want = np.asarray(S.contingency_table(
            jnp.asarray(np.minimum(X2[:, :3], 1.0)), jnp.asarray(Y)))
        np.testing.assert_allclose(st.contingency[:3], want,
                                   rtol=1e-5, atol=1e-3)
        want_unclipped = np.asarray(S.contingency_table(
            jnp.asarray(X2[:, 3:]), jnp.asarray(Y)))
        np.testing.assert_allclose(st.contingency[3:], want_unclipped,
                                   rtol=1e-4, atol=1e-3)

    def test_contingency_stats_host_matches_jit(self):
        rng = np.random.default_rng(6)
        table = rng.integers(1, 60, size=(3, 4)).astype(np.float64)
        host = S.contingency_stats_host(table)
        dev = S.contingency_stats(jnp.asarray(table, jnp.float32))
        assert abs(host.chi2 - float(dev.chi2)) / float(dev.chi2) < 1e-4
        assert abs(host.cramers_v - float(dev.cramers_v)) < 1e-5
        assert abs(host.mutual_info - float(dev.mutual_info)) < 1e-5
        np.testing.assert_allclose(host.max_rule_confidences,
                                   np.asarray(dev.max_rule_confidences),
                                   atol=1e-5)

    def test_fused_hist_matches_hist_numeric(self):
        from transmogrifai_tpu.filters.raw_feature_filter import \
            _hist_numeric
        X, y, _ = _data(seed=7)
        lo = np.nanmin(X, axis=0).astype(np.float32)
        hi = np.nanmax(X, axis=0).astype(np.float32)
        st = SE.run_stats(X, y, lo=lo, hi=hi, bins=16)
        assert st.hist.shape == (X.shape[1], 17)
        for j in range(X.shape[1]):
            want = _hist_numeric(X[:, j].astype(np.float64), 16,
                                 float(lo[j]), float(hi[j]))
            np.testing.assert_allclose(st.hist[j, :16], want)
            # missing bin carries the NaN mass
            assert st.hist[j, 16] == (~np.isfinite(X[:, j])).sum()

    def test_spearman_ranks_match_legacy(self):
        X, y, _ = _data(seed=8, d=4)
        rx, ry = SE.rank_matrices(X, y, col_block=3)  # ragged tail
        st = SE.run_stats(rx, ry)
        legacy = np.asarray(S.spearman_with_label(jnp.asarray(X),
                                                  jnp.asarray(y)))
        np.testing.assert_allclose(st.corr_label, legacy,
                                   rtol=1e-3, atol=1e-4)

    def test_empty_and_constant_columns(self):
        X, y, _ = _data(seed=9)
        X[:, 0] = np.nan          # empty
        X[:, 1] = 42.0            # constant
        st = SE.run_stats(X, y)
        assert st.count[0] == 0
        assert st.variance[1] == 0.0
        assert st.corr_label[0] == 0.0 and st.corr_label[1] == 0.0
        assert st.mean[1] == pytest.approx(42.0)
        assert st.fill_rate[0] == 0.0

    def test_label_moments(self):
        X, y, _ = _data(seed=10)
        st = SE.run_stats(X, y)
        yd = y.astype(np.float64)
        assert st.label_count == pytest.approx(len(y))
        assert st.label_mean == pytest.approx(yd.mean(), abs=1e-5)
        assert st.label_variance == pytest.approx(yd.var(ddof=1), rel=1e-4)
        assert st.label_min == yd.min() and st.label_max == yd.max()

    def test_gram_cap_raises(self):
        with pytest.raises(ValueError):
            SE.fused_stats(np.zeros((4, SE.GRAM_MAX_D + 1), np.float32),
                           np.zeros(4, np.float32), corr_matrix=True)


class TestDrivers:
    def test_streamed_matches_fused(self):
        X, y, rng = _data(seed=11)
        w = rng.choice([0.5, 1.0], size=len(y)).astype(np.float32)
        distinct = np.unique(y)
        fused = SE.run_stats(X, y, w, distinct=distinct, corr_matrix=True)
        streamed = SE.run_stats(X, y, w, distinct=distinct,
                                corr_matrix=True, driver="streamed",
                                tile_rows=100)
        for f in ("count", "mean", "variance", "min", "max", "corr_label",
                  "num_non_zeros", "fill_rate"):
            np.testing.assert_allclose(getattr(streamed, f),
                                       getattr(fused, f),
                                       rtol=2e-5, atol=2e-6, err_msg=f)
        np.testing.assert_allclose(streamed.corr_matrix, fused.corr_matrix,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(streamed.contingency, fused.contingency,
                                   rtol=1e-5, atol=1e-4)
        assert streamed.wsum == pytest.approx(fused.wsum, rel=1e-6)

    def test_sharded_matches_fused(self):
        from transmogrifai_tpu.parallel.mesh import make_mesh
        X, y, rng = _data(seed=12, n=514)  # ragged vs the 2-way mesh
        w = rng.choice([0.5, 1.0], size=len(y)).astype(np.float32)
        mesh = make_mesh(n_batch=2, n_model=1)
        fused = SE.run_stats(X, y, w, distinct=np.unique(y),
                             corr_matrix=True)
        sharded = SE.run_stats(X, y, w, distinct=np.unique(y),
                               corr_matrix=True, mesh=mesh)
        for f in ("count", "mean", "variance", "min", "max", "corr_label",
                  "fill_rate"):
            np.testing.assert_allclose(getattr(sharded, f),
                                       getattr(fused, f),
                                       rtol=3e-4, atol=3e-5, err_msg=f)
        np.testing.assert_allclose(sharded.corr_matrix, fused.corr_matrix,
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(sharded.contingency, fused.contingency,
                                   rtol=1e-4, atol=1e-3)


class TestSanityCheckerFused:
    def _fit(self, monkeypatch, fused, **kw):
        from transmogrifai_tpu.automl import SanityChecker
        from transmogrifai_tpu.data.dataset import (
            Column, column_from_values)
        from transmogrifai_tpu.data.vector import (
            VectorColumnMetadata, VectorMetadata)
        from transmogrifai_tpu.types import ColumnKind, RealNN

        monkeypatch.setenv("TMOG_STATS_FUSED", "1" if fused else "0")
        rng = np.random.default_rng(13)
        n = 600
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        cat = np.stack([y, 1 - y], axis=1)   # leaky indicator group
        X = np.concatenate(
            [rng.normal(size=(n, 1)), cat,
             np.full((n, 1), 3.0)], axis=1).astype(np.float32)
        meta = VectorMetadata(name="features", columns=[
            VectorColumnMetadata("num", "Real", descriptor_value="v",
                                 index=0),
            VectorColumnMetadata("cat", "PickList", grouping="cat",
                                 indicator_value="A", index=1),
            VectorColumnMetadata("cat", "PickList", grouping="cat",
                                 indicator_value="B", index=2),
            VectorColumnMetadata("const", "Real", descriptor_value="v",
                                 index=3),
        ])
        chk = SanityChecker(remove_bad_features=True, **kw)
        label = column_from_values(RealNN, [float(v) for v in y])
        vec = Column(kind=ColumnKind.VECTOR, data=X, metadata=meta)
        return chk.fit_columns(label, vec)

    def test_fused_matches_legacy_end_to_end(self, monkeypatch):
        m_fused = self._fit(monkeypatch, fused=True)
        m_legacy = self._fit(monkeypatch, fused=False)
        assert m_fused.indices_to_keep == m_legacy.indices_to_keep
        assert m_fused.summary.dropped == m_legacy.summary.dropped
        sf = m_fused.summary
        sl = m_legacy.summary
        for a, b in zip(sf.column_stats, sl.column_stats):
            for k in ("count", "mean", "min", "max"):
                assert a[k] == pytest.approx(b[k], rel=1e-4, abs=1e-5), k
            assert a["variance"] == pytest.approx(b["variance"],
                                                  rel=1e-3, abs=1e-5)
            if a["corr_label"] is not None and b["corr_label"] is not None:
                assert a["corr_label"] == pytest.approx(
                    b["corr_label"], rel=1e-3, abs=1e-4)
        assert len(sf.categorical_stats) == len(sl.categorical_stats) == 1
        ga, gb = sf.categorical_stats[0], sl.categorical_stats[0]
        assert ga["cramers_v"] == pytest.approx(gb["cramers_v"], rel=1e-4)
        assert ga["chi2"] == pytest.approx(gb["chi2"], rel=1e-3)
        assert ga["mutual_info"] == pytest.approx(gb["mutual_info"],
                                                  rel=1e-3, abs=1e-5)
        np.testing.assert_allclose(ga["contingency_matrix"],
                                   gb["contingency_matrix"], atol=1e-2)
        # compare the corr matrix on non-degenerate columns only: for the
        # constant column the legacy path's diagonal is 0/0 noise (tiny
        # centering residuals over tiny sd), the fused path's is a clean 0
        live = [0, 1, 2]
        cmf = np.asarray(sf.correlations_matrix)[np.ix_(live, live)]
        cml = np.asarray(sl.correlations_matrix)[np.ix_(live, live)]
        np.testing.assert_allclose(cmf, cml, rtol=1e-3, atol=2e-4)
        assert sf.label_distribution == sl.label_distribution

    def test_pearson_fit_is_exactly_one_pass(self, monkeypatch):
        """THE acceptance pin: a pearson-mode fit (moments + label corr +
        full corr matrix + categorical contingency) lands exactly ONE
        stats_pass span, and never touches the legacy per-statistic
        kernels (each monkeypatched to raise)."""
        def _boom(*a, **k):  # pragma: no cover - failure path
            raise AssertionError("legacy multi-pass kernel dispatched "
                                 "under TMOG_STATS_FUSED=1")

        for fn in ("col_stats", "pearson_with_label", "pearson_matrix",
                   "spearman_with_label", "contingency_table",
                   "contingency_stats"):
            monkeypatch.setattr(S, fn, _boom)
        collector.enable("test_one_pass")
        try:
            self._fit(monkeypatch, fused=True)
            spans = [s for s in collector.trace.spans
                     if s.name.startswith("stats_pass")]
            assert len(spans) == 1, [s.name for s in spans]
            sp = spans[0]
            assert sp.name == "stats_pass[fused]"
            assert sp.attrs["passes"] == 1
            assert sp.attrs["bytes_hbm"] == SE.stats_pass_bytes(600, 4)
            passes = collector.current.stats_metrics
            assert len(passes) == 1 and passes[0].driver == "fused"
        finally:
            collector.disable()
            collector.finish()

    def test_legacy_kill_switch_restores_multi_pass(self, monkeypatch):
        collector.enable("test_kill_switch")
        try:
            model = self._fit(monkeypatch, fused=False)
            spans = [s for s in collector.trace.spans
                     if s.name.startswith("stats_pass")]
            assert spans == []
            assert model.indices_to_keep == [0]
        finally:
            collector.disable()
            collector.finish()

    def test_spearman_fit_passes(self, monkeypatch):
        """Spearman keeps its rank pre-pass: one moment pass over X plus
        one over the ranks (still far below the legacy 4+G)."""
        collector.enable("test_spearman_passes")
        try:
            self._fit(monkeypatch, fused=True, correlation_type="spearman")
            spans = [s for s in collector.trace.spans
                     if s.name.startswith("stats_pass")]
            assert len(spans) == 2
            labels = {s.attrs.get("label") for s in spans}
            assert labels == {"sanity_stats", "sanity_spearman"}
        finally:
            collector.disable()
            collector.finish()

    def test_spearman_fused_matches_legacy(self, monkeypatch):
        mf = self._fit(monkeypatch, fused=True,
                       correlation_type="spearman")
        ml = self._fit(monkeypatch, fused=False,
                       correlation_type="spearman")
        for a, b in zip(mf.summary.column_stats, ml.summary.column_stats):
            if a["corr_label"] is not None and b["corr_label"] is not None:
                assert a["corr_label"] == pytest.approx(
                    b["corr_label"], rel=1e-3, abs=1e-4)


class TestRawFeatureFilterFused:
    def _ds(self, seed=14, n=400):
        from transmogrifai_tpu import Dataset
        from transmogrifai_tpu.types import Real
        rng = np.random.default_rng(seed)
        a = rng.normal(size=n)
        a[rng.uniform(size=n) < 0.2] = np.nan
        b = rng.normal(5, 2, size=n)
        empty = np.full(n, np.nan)
        return Dataset.from_features([
            ("a", Real, list(a)), ("b", Real, list(b)),
            ("empty", Real, list(empty))])

    def test_batched_matches_legacy(self, monkeypatch):
        from transmogrifai_tpu.filters import compute_distributions
        ds = self._ds()
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        fused = compute_distributions(ds, ["a", "b", "empty"], bins=20)
        monkeypatch.setenv("TMOG_STATS_FUSED", "0")
        legacy = compute_distributions(ds, ["a", "b", "empty"], bins=20)
        assert [d.name for d in fused] == [d.name for d in legacy]
        for f, l in zip(fused, legacy):
            assert (f.count, f.nulls) == (l.count, l.nulls)
            np.testing.assert_allclose(f.distribution, l.distribution,
                                       atol=1e-6, err_msg=f.name)
            np.testing.assert_allclose(f.summary, l.summary,
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f.name)

    def test_pinned_ranges_fuse_into_one_pass(self, monkeypatch):
        from transmogrifai_tpu.filters import compute_distributions
        ds = self._ds(seed=15)
        ranges = {"a": (-3.0, 3.0), "b": (-1.0, 11.0),
                  "empty": (0.0, 1.0)}
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        collector.enable("test_rff_fused_hist")
        try:
            fused = compute_distributions(ds, ["a", "b", "empty"],
                                          bins=10, ranges=ranges)
            passes = [m for m in collector.current.stats_metrics
                      if m.label == "rff_sketch"]
            assert len(passes) == 1  # histogram rode the moment pass
        finally:
            collector.disable()
            collector.finish()
        monkeypatch.setenv("TMOG_STATS_FUSED", "0")
        legacy = compute_distributions(ds, ["a", "b", "empty"],
                                       bins=10, ranges=ranges)
        for f, l in zip(fused, legacy):
            np.testing.assert_allclose(f.distribution, l.distribution,
                                       atol=1e-6, err_msg=f.name)

    def test_inf_values_keep_legacy_semantics(self, monkeypatch):
        """+/-inf is a VALID value (missing == NaN only): counts, sums
        and ranges must match the per-column legacy path, with inf mass
        clipped into the histogram edge bins."""
        from transmogrifai_tpu import Dataset
        from transmogrifai_tpu.filters import compute_distributions
        from transmogrifai_tpu.types import Real
        rng = np.random.default_rng(20)
        vals = list(rng.normal(size=40))
        col = vals + [np.inf, np.inf, -np.inf, None, None]
        ds = Dataset.from_features([
            ("r", Real, col), ("plain", Real, list(rng.normal(size=45)))])
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        fused = compute_distributions(ds, ["r", "plain"], bins=8)
        monkeypatch.setenv("TMOG_STATS_FUSED", "0")
        legacy = compute_distributions(ds, ["r", "plain"], bins=8)
        for f, l in zip(fused, legacy):
            assert (f.count, f.nulls) == (l.count, l.nulls), f.name
            np.testing.assert_allclose(f.summary, l.summary, rtol=1e-4,
                                       err_msg=f.name)
            np.testing.assert_allclose(f.distribution, l.distribution,
                                       atol=1e-6, err_msg=f.name)
        r = fused[0]
        assert r.nulls == 2 and r.count == 45          # inf is not null
        # mixed +/-inf: the sum degenerates to NaN on BOTH paths (the
        # parity loop above already pinned it); the point is it is not a
        # finite number silently missing the infs
        assert not np.isfinite(r.summary[2])

    def test_corr_matrix_cap_above_gram_limit_falls_back(self,
                                                         monkeypatch):
        """max_corr_matrix_columns raised past the engine's Gram cap must
        compute the matrix on the legacy kernel, not crash the fit."""
        from transmogrifai_tpu.automl import SanityChecker
        from transmogrifai_tpu.data.dataset import (
            Column, column_from_values)
        from transmogrifai_tpu.types import ColumnKind, RealNN
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        monkeypatch.setattr(SE, "GRAM_MAX_D", 4)  # shrink for the test
        rng = np.random.default_rng(21)
        X = rng.normal(size=(120, 6)).astype(np.float32)
        y = (rng.uniform(size=120) < 0.5).astype(np.float32)
        chk = SanityChecker(max_corr_matrix_columns=8)
        model = chk.fit_columns(
            column_from_values(RealNN, [float(v) for v in y]),
            Column(kind=ColumnKind.VECTOR, data=X))
        cm = np.asarray(model.summary.correlations_matrix)
        assert cm.shape == (6, 6)
        np.testing.assert_allclose(np.diag(cm), np.ones(6), atol=1e-5)

    def test_hist_numeric_shares_one_executable(self):
        from transmogrifai_tpu.filters.raw_feature_filter import \
            _hist_numeric
        v = np.random.default_rng(16).normal(size=300)
        _hist_numeric(v, 12, -1.0, 1.0)
        cache0 = S.histogram_batched._cache_size()
        _hist_numeric(v, 12, -2.5, 4.0)       # new ranges: traced, no
        _hist_numeric(v + 1, 12, 0.0, 2.0)    # retrace
        assert S.histogram_batched._cache_size() == cache0


class TestInsightsCorrFused:
    def _cols(self, seed=17):
        from transmogrifai_tpu.data.dataset import Column
        from transmogrifai_tpu.models.prediction import (
            make_prediction_column)
        from transmogrifai_tpu.types import ColumnKind
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(200, 3)).astype(np.float32)
        score = 1 / (1 + np.exp(-2 * X[:, 1]))
        pred = make_prediction_column(
            (score > 0.5).astype(np.float32),
            np.stack([-score, score], 1), np.stack([1 - score, score], 1))
        return Column(kind=ColumnKind.VECTOR, data=X), pred

    def test_small_batches_stay_on_numpy(self, monkeypatch):
        """Transform-time batches vary in shape; below the element
        threshold the engine (and its per-shape retrace) must not run."""
        from transmogrifai_tpu.insights import RecordInsightsCorr
        vec, pred = self._cols()
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        collector.enable("test_insights_small")
        try:
            RecordInsightsCorr(top_k=2).transform_columns(vec, pred)
            assert collector.current.stats_metrics == []
        finally:
            collector.disable()
            collector.finish()

    def test_fused_matches_legacy(self, monkeypatch):
        import json

        import transmogrifai_tpu.insights.corr as corr_mod
        from transmogrifai_tpu.insights import RecordInsightsCorr
        vec, pred = self._cols()
        monkeypatch.setattr(corr_mod, "_FUSED_MIN_ELEMENTS", 0)
        monkeypatch.setenv("TMOG_STATS_FUSED", "1")
        out_f = RecordInsightsCorr(top_k=3).transform_columns(vec, pred)
        monkeypatch.setenv("TMOG_STATS_FUSED", "0")
        out_l = RecordInsightsCorr(top_k=3).transform_columns(vec, pred)
        for mf, ml in zip(out_f.data, out_l.data):
            assert set(mf) == set(ml)
            for k in mf:
                a, b = json.loads(mf[k]), json.loads(ml[k])
                assert a["correlation"] == pytest.approx(
                    b["correlation"], rel=1e-3, abs=1e-4)
                assert a["contribution"] == pytest.approx(
                    b["contribution"], rel=1e-3, abs=1e-4)


class TestTelemetry:
    def test_stats_pass_record_and_json(self):
        collector.enable("test_stats_telemetry")
        try:
            X, y, _ = _data(seed=18)
            SE.run_stats(X, y, driver="streamed", tile_rows=128)
            rec = collector.current.stats_metrics[-1]
            assert rec.driver == "streamed"
            assert rec.rows == len(y) and rec.cols == X.shape[1]
            assert rec.tiles == -(-len(y) // 128)
            assert rec.passes == 1
            assert rec.bytes_hbm == SE.stats_pass_bytes(len(y), X.shape[1])
            doc = collector.current.to_json()
            assert "stats_metrics" in doc
            assert doc["stats_metrics"][-1]["driver"] == "streamed"
            # the roofline twin rides the kernel table (BENCH JSON slot)
            assert any(k.kernel == "stats_pass[streamed]"
                       for k in collector.current.kernel_metrics)
        finally:
            collector.disable()
            collector.finish()

    def test_stats_pass_event_on_log(self, tmp_path):
        import json
        log = str(tmp_path / "events.jsonl")
        collector.enable("test_stats_event")
        collector.attach_event_log(log)
        try:
            X, y, _ = _data(seed=19)
            SE.run_stats(X, y)
        finally:
            collector.detach_event_log()
            collector.disable()
            collector.finish()
        events = [json.loads(l) for l in open(log) if l.strip()]
        sp = [e for e in events if e["event"] == "stats_pass"]
        assert len(sp) == 1 and sp[0]["driver"] == "fused"

    def test_appmetrics_json_unchanged_without_stats(self):
        collector.enable("test_no_stats")
        try:
            doc = collector.current.to_json()
            assert "stats_metrics" not in doc
        finally:
            collector.disable()
            collector.finish()
