"""Kitchen-sink workflow save/load round trip (reference
OpWorkflowModelReaderWriterTest): one DAG exercising text hash + pivot,
dates, geo, real maps, numeric impute, sanity checker and a model
selector — scores must survive persistence bit-for-bit (atol 1e-5) and
the local row path must agree."""
import os
import tempfile

import numpy as np

from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.selectors import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.local.scoring import score_function
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.models.prediction import probability_of
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.types import (
    Date, Geolocation, PickList, Real, RealMap, RealNN, Text,
)
from transmogrifai_tpu.workflow.io import load_model
from transmogrifai_tpu.workflow.workflow import Workflow


def _build(n=500, seed=8):
    rng = np.random.default_rng(seed)
    cats = rng.choice(["red", "green", "blue", None], n,
                      p=[0.4, 0.3, 0.25, 0.05])
    words = ["alpha beta", "gamma delta words", "omega", None]
    txt = rng.choice(words, n)
    age = rng.uniform(18, 90, n)
    age[rng.uniform(size=n) < 0.1] = np.nan
    ts = (1.6e12 + rng.uniform(0, 1e10, n)).astype(np.int64)
    geo = [[float(rng.uniform(-60, 60)), float(rng.uniform(-120, 120)), 1.0]
           if rng.uniform() > 0.1 else None for _ in range(n)]
    mp = [{"k1": float(rng.normal()), "k2": float(rng.normal())}
          for _ in range(n)]
    score = ((cats == "red").astype(float) + 0.02 * np.nan_to_num(age, nan=45)
             + rng.normal(scale=0.5, size=n))
    y = (score > np.median(score)).astype(float)

    ds = Dataset.from_features([
        ("cat", PickList, [None if c is None else str(c) for c in cats]),
        ("txt", Text, [None if t is None else str(t) for t in txt]),
        ("age", Real, [None if np.isnan(v) else float(v) for v in age]),
        ("ts", Date, ts.tolist()),
        ("geo", Geolocation, geo),
        ("mp", RealMap, mp),
        ("label", RealNN, y.tolist()),
    ])
    feats = [
        FeatureBuilder.PickList("cat").extract(lambda r: r.get("cat")).as_predictor(),
        FeatureBuilder.Text("txt").extract(lambda r: r.get("txt")).as_predictor(),
        FeatureBuilder.Real("age").extract(lambda r: r.get("age")).as_predictor(),
        FeatureBuilder.Date("ts").extract(lambda r: r.get("ts")).as_predictor(),
        FeatureBuilder.Geolocation("geo").extract(lambda r: r.get("geo")).as_predictor(),
        FeatureBuilder.RealMap("mp").extract(lambda r: r.get("mp")).as_predictor(),
    ]
    fy = FeatureBuilder.RealNN("label").extract(lambda r: r.get("label")).as_response()
    vec = transmogrify(feats)
    checked = SanityChecker().set_input(fy, vec).get_output()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[
            (OpLogisticRegression(max_iter=15), param_grid(reg_param=[0.01])),
            (OpGBTClassifier(max_iter=5, max_depth=3), param_grid()),
        ]).set_input(fy, checked).get_output()
    return ds, pred


def test_kitchen_sink_save_load_score_parity():
    ds, pred = _build()
    model = Workflow().set_input_dataset(ds).set_result_features(pred).train()
    p1 = probability_of(model.score(ds).column(pred.name))

    d = tempfile.mkdtemp()
    path = os.path.join(d, "model")
    model.save(path)
    m2 = load_model(path)
    p2 = probability_of(m2.score(ds).column(pred.name))
    np.testing.assert_allclose(p1, p2, atol=1e-5)

    # local row path on the RELOADED model agrees with batch
    fn = score_function(m2)
    row = {"cat": "red", "txt": "alpha beta", "age": 33.0,
           "ts": 1_600_000_000_000, "geo": [10.0, 20.0, 1.0],
           "mp": {"k1": 0.5, "k2": -0.2}}
    out = fn(dict(row))[pred.name]
    rv = dict(out.value if hasattr(out, "value") else out)
    assert 0.0 <= float(rv["probability_1"]) <= 1.0

    # summary survives the round trip (ModelSelectorSummary content)
    s = m2.summary()
    assert s and "best_model_type" in str(s)
