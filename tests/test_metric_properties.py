"""Property tests for the metric kernels against independent naive-numpy
implementations on randomized weighted data.

Reference test analogues: core/src/test/.../evaluators/
OpBinaryClassificationEvaluatorTest.scala etc. — here the oracle is a
from-first-principles numpy computation rather than Spark, exercising ties,
weights, degenerate labels, and multiclass confusion accounting.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from transmogrifai_tpu.ops import metrics_ops as M


def _rand_case(seed, n=400, tie_frac=0.3):
    rng = np.random.default_rng(seed)
    scores = rng.normal(size=n)
    # force heavy ties: quantize a fraction of scores
    tie = rng.uniform(size=n) < tie_frac
    scores[tie] = np.round(scores[tie], 1)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-scores * 0.8
                                               + rng.normal(size=n)))
         ).astype(np.float64)
    w = rng.choice([0.0, 0.5, 1.0, 2.0], size=n,
                   p=[0.1, 0.2, 0.5, 0.2]).astype(np.float64)
    return scores, y, w


def _naive_auroc(scores, y, w):
    """Weighted probability that a positive outranks a negative, ties = 1/2
    (the Mann-Whitney definition AuROC must equal)."""
    pos = np.flatnonzero((y > 0) & (w > 0))
    neg = np.flatnonzero((y <= 0) & (w > 0))
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    sp, sn = scores[pos], scores[neg]
    wp, wn = w[pos], w[neg]
    gt = (sp[:, None] > sn[None, :]).astype(np.float64)
    eq = (sp[:, None] == sn[None, :]).astype(np.float64)
    ww = wp[:, None] * wn[None, :]
    return float((ww * (gt + 0.5 * eq)).sum() / ww.sum())


def _naive_aupr(scores, y, w):
    """Average precision over descending tie-group boundaries."""
    order = np.argsort(-scores, kind="stable")
    s, yy, ww = scores[order], y[order], w[order]
    tp = np.cumsum(yy * ww)
    fp = np.cumsum((1 - yy) * ww)
    P = tp[-1]
    if P <= 0:
        return 0.0
    boundary = np.append(s[:-1] != s[1:], True)
    rec = tp / P
    prec = tp / np.maximum(tp + fp, 1e-12)
    r_prev, acc = 0.0, 0.0
    for i in np.flatnonzero(boundary):
        acc += (rec[i] - r_prev) * prec[i]
        r_prev = rec[i]
    return float(acc)


@pytest.mark.parametrize("seed", range(6))
def test_auroc_matches_mann_whitney(seed):
    scores, y, w = _rand_case(seed)
    got = float(M.au_roc(jnp.asarray(scores), jnp.asarray(y), jnp.asarray(w)))
    want = _naive_auroc(scores, y, w)
    assert abs(got - want) < 1e-5, (got, want)


@pytest.mark.parametrize("seed", range(6))
def test_aupr_matches_average_precision(seed):
    scores, y, w = _rand_case(seed)
    got = float(M.au_pr(jnp.asarray(scores), jnp.asarray(y), jnp.asarray(w)))
    want = _naive_aupr(scores, y, w)
    assert abs(got - want) < 1e-5, (got, want)


def test_degenerate_labels_do_not_nan():
    n = 50
    scores = np.linspace(-1, 1, n)
    for y in (np.zeros(n), np.ones(n)):
        for fn in (M.au_roc, M.au_pr, M.au_roc_binned, M.au_pr_binned):
            v = float(fn(jnp.asarray(scores), jnp.asarray(y)))
            assert np.isfinite(v), (fn.__name__, y[0], v)


@pytest.mark.parametrize("seed", range(4))
def test_binary_confusion_counts(seed):
    scores, y, w = _rand_case(seed)
    thr = 0.25
    m = M.binary_metrics(jnp.asarray(scores), jnp.asarray(y),
                         jnp.asarray(w), threshold=thr)
    pred = scores >= thr
    tp = float((w * (pred & (y > 0))).sum())
    tn = float((w * (~pred & (y <= 0))).sum())
    fp = float((w * (pred & (y <= 0))).sum())
    fn = float((w * (~pred & (y > 0))).sum())
    assert abs(float(m.tp) - tp) < 1e-4
    assert abs(float(m.tn) - tn) < 1e-4
    assert abs(float(m.fp) - fp) < 1e-4
    assert abs(float(m.fn) - fn) < 1e-4
    prec = tp / max(tp + fp, 1e-12)
    rec = tp / max(tp + fn, 1e-12)
    f1 = 2 * prec * rec / max(prec + rec, 1e-12)
    assert abs(float(m.precision) - prec) < 1e-5
    assert abs(float(m.recall) - rec) < 1e-5
    assert abs(float(m.f1) - f1) < 1e-5
    assert abs(float(m.error) - (fp + fn) / max(tp + tn + fp + fn, 1e-12)) \
        < 1e-5


@pytest.mark.parametrize("seed", range(4))
def test_multiclass_metrics_match_confusion(seed):
    rng = np.random.default_rng(seed)
    n, c = 300, 4
    y = rng.integers(0, c, size=n).astype(np.float64)
    pred = np.where(rng.uniform(size=n) < 0.7, y,
                    rng.integers(0, c, size=n)).astype(np.float64)
    w = rng.choice([0.0, 1.0, 2.0], size=n).astype(np.float64)
    m = M.multiclass_metrics(jnp.asarray(pred), jnp.asarray(y), c,
                             jnp.asarray(w))
    conf = np.zeros((c, c))
    for p_, y_, w_ in zip(pred, y, w):
        conf[int(y_), int(p_)] += w_
    total = conf.sum()
    err = 1.0 - np.trace(conf) / total
    assert abs(float(m.error) - err) < 1e-5
    # Spark weightedPrecision/weightedRecall convention (the reference's
    # OpMultiClassificationEvaluator): support-weighted per-class averages
    support = conf.sum(axis=1)
    sw = support / support.sum()
    prec_c = np.array([conf[k, k] / max(conf[:, k].sum(), 1e-12)
                       for k in range(c)])
    rec_c = np.array([conf[k, k] / max(support[k], 1e-12)
                      for k in range(c)])
    f1_c = 2 * prec_c * rec_c / np.maximum(prec_c + rec_c, 1e-12)
    assert abs(float(m.precision) - float((prec_c * sw).sum())) < 1e-5
    assert abs(float(m.recall) - float((rec_c * sw).sum())) < 1e-5
    assert abs(float(m.f1) - float((f1_c * sw).sum())) < 1e-5


@pytest.mark.parametrize("seed", range(4))
def test_regression_metrics_formulas(seed):
    rng = np.random.default_rng(seed)
    n = 200
    y = rng.normal(size=n)
    pred = y + rng.normal(size=n) * 0.3
    w = rng.choice([0.5, 1.0, 2.0], size=n)
    m = M.regression_metrics(jnp.asarray(pred), jnp.asarray(y),
                             jnp.asarray(w))
    wsum = w.sum()
    mse = float((w * (pred - y) ** 2).sum() / wsum)
    mae = float((w * np.abs(pred - y)).sum() / wsum)
    ybar = (w * y).sum() / wsum
    r2 = 1.0 - (w * (pred - y) ** 2).sum() / (w * (y - ybar) ** 2).sum()
    assert abs(float(m.mse) - mse) < 1e-6
    assert abs(float(m.rmse) - np.sqrt(mse)) < 1e-6
    assert abs(float(m.mae) - mae) < 1e-6
    assert abs(float(m.r2) - r2) < 1e-5
