"""Production serving engine (serve/): shape-bucketed micro-batching with
AOT-prewarmed executables (docs/serving.md).

Pins the subsystem's contracts: bucket-ladder shapes, request/batch parity
with the batch score path AND the local per-record replay, typed 400-class
validation errors, micro-batch coalescing + Overloaded load-shed +
graceful drain, the HTTP frontend's status-code mapping, the
streaming-quantile latency histogram, ZERO true XLA compiles after warmup
under concurrent mixed-batch-size traffic (RecompileTracker), and the
deploy-time prewarm: `serve --prewarm-only` followed by a fresh-process
start performs 0 true compiles (persistent-cache hits only).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.automl import BinaryClassificationModelSelector
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.local.scoring import (InvalidFeatureError,
                                             MissingFeatureError,
                                             UnknownFeatureError)
from transmogrifai_tpu.models.glm import OpLogisticRegression
from transmogrifai_tpu.readers.readers import ListReader
from transmogrifai_tpu.serve import (MicroBatcher, Overloaded, ServeFrontend,
                                     ServingEngine, bucket_ladder,
                                     make_http_server, template_record)
from transmogrifai_tpu.stages.params import param_grid
from transmogrifai_tpu.utils import tracing
from transmogrifai_tpu.utils.metrics import LatencyHistogram, collector
from transmogrifai_tpu.workflow import Workflow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_rows(n=400, seed=3):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a = float(rng.normal())
        b = float(rng.normal())
        rows.append({"a": a, "b": b, "c": str(rng.choice(["x", "y", "z"])),
                     "y": float(a + 0.5 * b > 0)})
    return rows


def _fit_model(rows):
    """Workflow whose scoring DAG contains JITTED stages (the derived
    math features) — compile counting must measure something real."""
    fa = FeatureBuilder.Real("a").extract(lambda r: r.get("a")).as_predictor()
    fb = FeatureBuilder.Real("b").extract(lambda r: r.get("b")).as_predictor()
    fc = FeatureBuilder.PickList("c").extract(
        lambda r: r.get("c")).as_predictor()
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    fsum = (fa + fb) + 1.0
    fnorm = fa.fill_missing_with_mean().z_normalize()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(max_iter=15),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify([fa, fb, fc, fsum, fnorm])).get_output()
    model = Workflow().set_reader(ListReader(rows)) \
        .set_result_features(pred).train()
    return model, pred


@pytest.fixture(scope="module")
def fitted():
    rows = _make_rows()
    model, pred = _fit_model(rows)
    return model, rows, pred


@pytest.fixture()
def collected():
    """Span collection + active RecompileTracker around one test."""
    collector.enable("test_serving")
    try:
        yield collector
    finally:
        collector.finish()
        collector.disable()


class TestBucketLadder:
    def test_ladder_shapes(self):
        assert bucket_ladder(64) == (1, 8, 16, 32, 64)
        assert bucket_ladder(1) == (1,)
        assert bucket_ladder(8) == (1, 8)
        # top rung rounds UP to a power of two
        assert bucket_ladder(100) == (1, 8, 16, 32, 64, 128)
        assert bucket_ladder(5) == (1, 8)

    def test_pick_bucket(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model, max_batch=64)
        assert eng.pick_bucket(1) == 1
        assert eng.pick_bucket(2) == 8
        assert eng.pick_bucket(8) == 8
        assert eng.pick_bucket(9) == 16
        assert eng.pick_bucket(64) == 64
        with pytest.raises(ValueError, match="exceeds max bucket"):
            eng.pick_bucket(65)

    def test_explicit_buckets_and_validation(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model, buckets=[4, 1, 32])
        assert eng.buckets == (1, 4, 32)
        assert eng.max_batch == 32
        with pytest.raises(ValueError, match="bucket sizes"):
            ServingEngine(model, buckets=[0, 4])
        with pytest.raises(ValueError, match="single_record"):
            ServingEngine(model, single_record="nope")

    def test_template_record(self, fitted):
        model, _, _ = fitted
        t = template_record(model.raw_features())
        assert set(t) == {"a", "b", "c"}  # responses excluded
        assert t["a"] == 0.0 and t["c"] == ""


class TestLatencyHistogram:
    def test_quantiles_track_percentiles(self):
        rng = np.random.default_rng(0)
        vals = rng.lognormal(mean=-5.0, sigma=1.0, size=5000)  # ~ms scale
        h = LatencyHistogram("t")
        for v in vals:
            h.record(float(v))
        for q in (0.5, 0.95, 0.99):
            est = h.quantile(q)
            true = float(np.quantile(vals, q))
            # log-bucketed: relative error bounded by the bucket ratio
            assert true / 1.6 <= est <= true * 1.6, (q, est, true)
        assert h.count == 5000
        assert h.max_seconds == pytest.approx(float(vals.max()))

    def test_json_fields_and_empty(self):
        h = LatencyHistogram("x")
        doc = h.to_json()
        assert doc["count"] == 0 and doc["p50_ms"] == 0.0
        h.record(0.010)
        doc = h.to_json()
        assert doc["count"] == 1 and doc["max_ms"] == 10.0
        assert doc["buckets_ms"]
        assert 2.0 < doc["p50_ms"] < 15.0

    def test_monotone_quantiles(self):
        h = LatencyHistogram("m")
        for v in (0.001, 0.002, 0.01, 0.2, 1.5):
            h.record(v)
        qs = [h.quantile(q) for q in (0.1, 0.5, 0.9, 0.99, 1.0)]
        assert qs == sorted(qs)

    def test_collector_latency_rides_appmetrics(self, collected):
        collected.latency("serve_total", 0.005)
        collected.latency("serve_total", 0.007)
        doc = collected.current.to_json()
        assert doc["latency_metrics"]["serve_total"]["count"] == 2

    def test_appmetrics_json_unchanged_without_latency(self):
        from transmogrifai_tpu.utils.metrics import AppMetrics
        assert "latency_metrics" not in AppMetrics().to_json()


class TestEngineScoring:
    def test_parity_with_batch_and_local(self, fitted):
        model, rows, pred = fitted
        eng = ServingEngine(model, max_batch=16)
        eng.prewarm()
        recs = [{k: v for k, v in r.items() if k != "y"}
                for r in rows[:10]]
        served = eng.score_batch(recs)
        scored = model.score()
        col = scored.column(pred.name)
        fn = model.score_function()
        from transmogrifai_tpu.models.prediction import probability_of
        probs = probability_of(col)
        for i, out in enumerate(served):
            rv = out[pred.name]
            assert isinstance(rv, dict)
            assert rv["probability_1"] == pytest.approx(
                float(probs[i, 1]), abs=1e-5)
            loc = fn(dict(recs[i]))[pred.name]
            loc = dict(loc.value if hasattr(loc, "value") else loc)
            assert rv["prediction"] == pytest.approx(
                float(loc["prediction"]), abs=1e-5)

    def test_padding_does_not_leak_into_results(self, fitted):
        model, rows, pred = fitted
        eng = ServingEngine(model, max_batch=16)
        recs = [{k: v for k, v in r.items() if k != "y"}
                for r in rows[:3]]
        out = eng.score_batch(recs)  # bucket 8, 5 pad rows
        assert len(out) == 3
        # one-at-a-time scores agree with the padded-batch scores
        for r, o in zip(recs, out):
            single = eng.score_batch([dict(r)])[0]
            assert single[pred.name]["prediction"] == \
                pytest.approx(o[pred.name]["prediction"], abs=1e-5)

    def test_bulk_chunks_above_max_batch(self, fitted):
        model, rows, _ = fitted
        eng = ServingEngine(model, buckets=[1, 8])
        recs = [{k: v for k, v in r.items() if k != "y"}
                for r in rows[:20]]
        assert len(eng.score_batch(recs)) == 20

    def test_single_record_local_route_parity(self, fitted):
        model, rows, pred = fitted
        bucket = ServingEngine(model, max_batch=8)
        local = ServingEngine(model, max_batch=8, single_record="local")
        bucket.prewarm()
        local.prewarm()
        rec = {k: v for k, v in rows[5].items() if k != "y"}
        b = bucket.score_record(dict(rec))[pred.name]
        l = local.score_record(dict(rec))[pred.name]
        assert l["prediction"] == pytest.approx(b["prediction"], abs=1e-5)
        assert l["probability_1"] == pytest.approx(b["probability_1"],
                                                   abs=1e-5)

    def test_missing_optional_key_scores(self, fitted):
        model, _, pred = fitted
        eng = ServingEngine(model, max_batch=8)
        out = eng.score_batch([{"a": 0.5}])  # b, c absent -> None/missing
        assert pred.name in out[0]

    def test_metrics_counters(self, fitted):
        model, rows, _ = fitted
        eng = ServingEngine(model, max_batch=8)
        eng.prewarm()
        eng.score_batch([{k: v for k, v in rows[0].items() if k != "y"}])
        m = eng.metrics()
        assert m["warm"] and m["rows"] >= 1 and m["batches"] >= 1
        assert m["latency"]["device_score"]["count"] >= 1
        assert m["post_warmup_compiles"] == 0


class TestRecordValidation:
    def test_unknown_key_typed_error(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model)
        with pytest.raises(UnknownFeatureError, match="bogus"):
            eng.validate_record({"a": 1.0, "bogus": 2.0})

    def test_non_strict_allows_extra_keys(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model, strict_keys=False)
        eng.validate_record({"a": 1.0, "row_id": "r1"})  # no raise

    def test_missing_feature_named(self):
        rows = _make_rows(200)
        # hard [] access: a missing key used to KeyError deep in a stage
        fa = FeatureBuilder.Real("a").extract(
            lambda r: r["a"]).as_predictor()
        fy = FeatureBuilder.RealNN("y").extract(
            lambda r: r.get("y")).as_response()
        pred = BinaryClassificationModelSelector \
            .with_train_validation_split(
                models_and_parameters=[(OpLogisticRegression(),
                                        param_grid(reg_param=[0.01]))],
            ).set_input(fy, transmogrify([fa])).get_output()
        model = Workflow().set_reader(ListReader(rows)) \
            .set_result_features(pred).train()
        eng = ServingEngine(model, strict_keys=False)
        with pytest.raises(MissingFeatureError, match="'a'"):
            eng.validate_record({"b": 1.0})
        # the per-record replay raises the SAME typed error
        with pytest.raises(MissingFeatureError, match="'a'"):
            model.score_function()({"b": 1.0})
        # MissingFeatureError still satisfies a legacy KeyError handler
        assert issubclass(MissingFeatureError, KeyError)

    def test_invalid_value_typed_error(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model)
        with pytest.raises(InvalidFeatureError, match="'a'"):
            eng.validate_record({"a": "not-a-number"})

    def test_record_must_be_dict(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model)
        with pytest.raises(InvalidFeatureError):
            eng.validate_record([1, 2, 3])


class TestZeroRecompilesUnderTraffic:
    def test_concurrent_mixed_batch_sizes(self, fitted, collected):
        """THE acceptance pin: after prewarm, concurrent traffic at every
        batch size in [1, max_batch] performs zero true XLA compiles —
        every shape the device sees is a prewarmed bucket."""
        model, rows, pred = fitted
        eng = ServingEngine(model, max_batch=16)
        eng.prewarm()
        base = tracing.tracker.true_compiles
        batcher = MicroBatcher(eng, max_wait_ms=3.0, max_queue=256)
        recs = [{k: v for k, v in r.items() if k != "y"} for r in rows]
        errors = []

        def single(i):
            try:
                out = batcher.submit(dict(recs[i % len(recs)]))
                assert pred.name in out
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        def bulk(k):
            try:
                out = eng.score_batch(
                    [dict(r) for r in recs[:k]])
                assert len(out) == k
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=single, args=(i,))
                   for i in range(24)]
        threads += [threading.Thread(target=bulk, args=(k,))
                    for k in (1, 2, 5, 8, 11, 16, 3, 13)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        batcher.shutdown(drain=True)
        assert not errors, errors[:3]
        assert tracing.tracker.true_compiles == base
        assert eng.post_warmup_compiles == 0
        m = eng.metrics()
        assert m["requests"] >= 24
        assert m["latency"]["total"]["count"] >= 24


class TestMicroBatcher:
    def _engine_stub(self, fitted, delay=0.0):
        model, _, _ = fitted
        eng = ServingEngine(model, max_batch=8)
        eng.prewarm()
        calls = []
        real = eng.score_batch

        def spy(records):
            calls.append(len(records))
            if delay:
                time.sleep(delay)
            return real(records)

        # tmoglint: disable=THR001  test fixture patches BEFORE threads
        eng.score_batch = spy
        return eng, calls

    def test_coalesces_concurrent_submits(self, fitted):
        eng, calls = self._engine_stub(fitted, delay=0.05)
        b = MicroBatcher(eng, max_wait_ms=100.0, max_queue=64)
        results = []
        ths = [threading.Thread(
            target=lambda i=i: results.append(
                b.submit({"a": 0.1 * i, "b": 0.0, "c": "x"})))
            for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(30)
        b.shutdown()
        assert len(results) == 6
        # 6 near-simultaneous submits must NOT make 6 device batches
        # (first dispatch may race ahead with fewer; never one-per-request)
        assert len(calls) < 6
        assert sum(calls) == 6

    def test_overload_sheds_typed(self, fitted):
        eng, _ = self._engine_stub(fitted, delay=0.3)
        b = MicroBatcher(eng, max_wait_ms=0.0, max_queue=2)

        def sub():
            try:
                b.submit({"a": 1.0, "b": 0.0, "c": "x"})
            except Overloaded:
                pass  # racing threads may be shed too — that's the point

        ths = [threading.Thread(target=sub) for _ in range(4)]
        for t in ths:
            t.start()
        time.sleep(0.1)  # dispatcher busy on batch 1, queue refills
        with b._cond:
            while len(b._q) < b.max_queue:  # fill whatever room is left
                from transmogrifai_tpu.serve.batcher import _Pending
                b._q.append(_Pending({"a": 0.0, "b": 0.0, "c": "x"}))
        with pytest.raises(Overloaded):
            b.submit({"a": 2.0, "b": 0.0, "c": "x"})
        assert eng.n_shed >= 1
        b.shutdown(drain=True)
        for t in ths:
            t.join(30)

    def test_graceful_drain_scores_everything(self, fitted):
        eng, calls = self._engine_stub(fitted, delay=0.05)
        b = MicroBatcher(eng, max_wait_ms=50.0, max_queue=64)
        results, errs = [], []

        def sub(i):
            try:
                results.append(b.submit({"a": float(i), "b": 0.0,
                                         "c": "y"}))
            except Exception as e:
                errs.append(e)

        ths = [threading.Thread(target=sub, args=(i,)) for i in range(10)]
        for t in ths:
            t.start()
        time.sleep(0.02)
        b.shutdown(drain=True)  # refuse new, score queued
        for t in ths:
            t.join(30)
        assert not errs
        assert len(results) == 10
        assert sum(calls) == 10

    def test_timeout_withdraws_queued_request(self, fitted):
        """A timed-out submit must pull its request back OUT of the
        queue: it is neither scored nor counted, and stops holding
        queue capacity (review finding)."""
        eng, calls = self._engine_stub(fitted, delay=0.4)
        b = MicroBatcher(eng, max_wait_ms=0.0, max_queue=8)
        # occupy the dispatcher so the next submit stays queued
        t1 = threading.Thread(
            target=lambda: b.submit({"a": 1.0, "b": 0.0, "c": "x"}))
        t1.start()
        time.sleep(0.1)
        n_req0 = eng.n_requests
        with pytest.raises(TimeoutError):
            b.submit({"a": 2.0, "b": 0.0, "c": "x"}, timeout=0.05)
        t1.join(30)
        b.shutdown(drain=True)
        # the withdrawn record never reached the engine
        assert sum(calls) == 1
        assert eng.n_requests == n_req0 + 1  # only the live request

    def test_submit_after_shutdown_raises(self, fitted):
        eng, _ = self._engine_stub(fitted)
        b = MicroBatcher(eng)
        b.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            b.submit({"a": 1.0, "b": 0.0, "c": "x"})

    def test_systemic_error_propagates_to_waiters(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model, max_batch=8)
        eng.prewarm()

        def boom(records):
            raise RuntimeError("device on fire")

        eng.score_batch = boom
        b = MicroBatcher(eng, max_wait_ms=1.0)
        with pytest.raises(RuntimeError, match="device on fire"):
            b.submit({"a": 1.0, "b": 0.0, "c": "x"}, timeout=30)
        b.shutdown()

    def test_validation_rejected_before_admission(self, fitted):
        eng, calls = self._engine_stub(fitted)
        b = MicroBatcher(eng)
        with pytest.raises(UnknownFeatureError):
            b.submit({"a": 1.0, "nope": 1.0})
        b.shutdown()
        assert sum(calls) == 0  # never reached the engine


class TestHTTPFrontend:
    @pytest.fixture()
    def server(self, fitted):
        model, _, pred = fitted
        eng = ServingEngine(model, max_batch=8)
        eng.prewarm()
        batcher = MicroBatcher(eng, max_wait_ms=2.0)
        fe = ServeFrontend(eng, batcher)
        httpd = make_http_server(fe)
        th = threading.Thread(target=httpd.serve_forever,
                              kwargs={"poll_interval": 0.05}, daemon=True)
        th.start()
        yield httpd.server_address[1], pred
        httpd.shutdown()
        httpd.server_close()
        batcher.shutdown()

    def _req(self, port, path, payload=None):
        import urllib.error
        import urllib.request
        url = f"http://127.0.0.1:{port}{path}"
        if payload is None:
            req = urllib.request.Request(url)
        else:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_score_single_and_bulk(self, server):
        port, pred = server
        code, out = self._req(port, "/score",
                              {"a": 0.3, "b": -0.1, "c": "x"})
        assert code == 200 and pred.name in out
        code, out = self._req(port, "/score",
                              [{"a": 0.1, "b": 0.0, "c": "y"},
                               {"a": -0.4, "b": 1.0, "c": "z"}])
        assert code == 200 and len(out) == 2

    def test_client_errors_are_400(self, server):
        port, _ = server
        code, out = self._req(port, "/score", {"a": 1.0, "junk": 1})
        assert code == 400 and out["error_type"] == "UnknownFeatureError"
        code, out = self._req(port, "/score", 42)
        assert code == 400

    def test_healthz_and_metrics(self, server):
        port, _ = server
        code, h = self._req(port, "/healthz")
        assert code == 200 and h["warm"] is True
        self._req(port, "/score", {"a": 0.0, "b": 0.0, "c": "x"})
        code, m = self._req(port, "/metrics")
        assert code == 200
        assert m["requests"] >= 1
        assert "p99_ms" in m["latency"]["total"]

    def test_unknown_path_404(self, server):
        port, _ = server
        code, _ = self._req(port, "/nope")
        assert code == 404

    def test_drain_flips_healthz_and_drops_nothing(self, server):
        """The /drain satellite (docs/fleet.md): GET /drain flips
        /healthz to draining-503 so a router/LB rotates the replica out
        BEFORE SIGTERM — while every in-flight and still-arriving
        request keeps scoring (the no-dropped-requests pin)."""
        port, pred = server
        errors, oks = [], []

        def fire(n):
            for _ in range(n):
                try:
                    code, out = self._req(port, "/score",
                                          {"a": 0.1, "b": 0.2, "c": "x"})
                    assert code == 200 and pred.name in out, (code, out)
                    oks.append(1)
                except Exception as e:  # noqa: BLE001 - tallied below
                    errors.append(repr(e))

        threads = [threading.Thread(target=fire, args=(8,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        # flip the drain mid-traffic
        code, d = self._req(port, "/drain")
        assert code == 200 and d["draining"] is True
        assert d["status"] == "draining"
        for t in threads:
            t.join(60)
        assert not errors, errors[:3]
        assert len(oks) == 32  # nothing dropped
        # the LB view: healthz is 503/draining, idempotently
        code, h = self._req(port, "/healthz")
        assert code == 503 and h["status"] == "draining"
        code, h = self._req(port, "/drain")
        assert code == 200 and h["status"] == "draining"
        # ... and scoring STILL works (drain is rotation, not refusal)
        code, out = self._req(port, "/score",
                              {"a": 0.0, "b": 0.0, "c": "y"})
        assert code == 200 and pred.name in out

    def test_bulk_above_max_bulk_is_413(self, fitted):
        model, _, _ = fitted
        eng = ServingEngine(model, max_batch=8)
        batcher = MicroBatcher(eng, max_wait_ms=1.0)
        fe = ServeFrontend(eng, batcher, max_bulk=3)
        httpd = make_http_server(fe)
        th = threading.Thread(target=httpd.serve_forever,
                              kwargs={"poll_interval": 0.05}, daemon=True)
        th.start()
        try:
            code, out = self._req(
                httpd.server_address[1], "/score",
                [{"a": 0.0, "b": 0.0, "c": "x"}] * 4)
            assert code == 413 and "max_bulk" in out["error"]
        finally:
            httpd.shutdown()
            httpd.server_close()
            batcher.shutdown()


class TestServeEvents:
    def test_events_and_trace_check(self, fitted, collected, tmp_path):
        model, rows, _ = fitted
        collected.attach_event_log(str(tmp_path / "events.jsonl"))
        try:
            eng = ServingEngine(model, max_batch=8)
            eng.prewarm()
            b = MicroBatcher(eng, max_wait_ms=1.0)
            b.submit({k: v for k, v in rows[0].items() if k != "y"})
            eng.note_shed(queue_len=5)  # the shed path's event
            b.shutdown(drain=True)
            collected.save_chrome_trace(str(tmp_path / "serve_trace.json"),
                                        close=False)
        finally:
            collected.detach_event_log()
        events = [json.loads(l) for l in
                  (tmp_path / "events.jsonl").read_text().splitlines()]
        kinds = {e["event"] for e in events}
        assert {"serve_prewarm", "serve_batch", "serve_request",
                "serve_shed"} <= kinds
        assert "serve_recompile" not in kinds
        from transmogrifai_tpu.utils.tracing import trace_report
        text, ok = trace_report(str(tmp_path), check=True)
        assert ok, text
        # serve spans land in the exported trace
        doc = json.loads((tmp_path / "serve_trace.json").read_text())
        names = {e.get("name") for e in doc["traceEvents"]}
        assert {"batch_assemble", "device_score", "queue_wait"} <= names

    def test_trace_check_fails_on_post_warmup_recompile(self, tmp_path):
        (tmp_path / "events.jsonl").write_text(
            json.dumps({"seq": 0, "t": 0.0, "ts": 0.0,
                        "event": "serve_recompile", "compiles": 1}) + "\n")
        from transmogrifai_tpu.utils.tracing import trace_report
        text, ok = trace_report(str(tmp_path), check=True)
        assert not ok
        assert "serve_recompile" in text


class TestManifestFreshness:
    """The serve.json freshness stamp (docs/fleet.md "The manifest
    contract"): --prewarm-only stamps model hash + monitor presence;
    adoption verifies both — warning by default, rc 2 under
    --strict-manifest (how a fleet replica refuses to join)."""

    def _saved(self, fitted, tmp_path):
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        model, _, _ = fitted
        mdir = str(tmp_path / "model")
        model.save(mdir)
        m2 = WorkflowModel.load(mdir)
        eng = ServingEngine(m2, buckets=[1, 4])
        eng.write_manifest()
        return mdir

    def test_fresh_manifest_verifies_clean(self, fitted, tmp_path):
        from transmogrifai_tpu.workflow.io import load_serve_manifest
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        mdir = self._saved(fitted, tmp_path)
        manifest = load_serve_manifest(mdir)
        assert manifest["model_hash"] and len(manifest["model_hash"]) == 16
        assert isinstance(manifest["monitor_profile"], bool)
        eng = ServingEngine(WorkflowModel.load(mdir))
        assert eng.manifest_mismatch == []

    def test_stale_hash_warns_and_strict_refuses(self, fitted, tmp_path):
        import argparse
        from transmogrifai_tpu.serve.frontend import run_serve
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        mdir = self._saved(fitted, tmp_path)
        # the model is re-saved/modified AFTER the prewarm stamped it
        with open(os.path.join(mdir, "arrays.npz"), "ab") as f:
            f.write(b"drift")
        eng = ServingEngine(WorkflowModel.load(mdir))
        assert eng.manifest_mismatch  # adoption NOTICED (warning path)
        assert any("model_hash" in p for p in eng.manifest_mismatch)
        # --strict-manifest: the same staleness is a startup refusal
        args = argparse.Namespace(
            model_dir=mdir, max_batch=8, buckets=None, example=None,
            single_record="bucket", monitor="off", metrics_location=None,
            strict_manifest=True, prewarm_only=True)
        assert run_serve(args) == 2

    def test_explicit_bucket_disagreement_is_flagged(self, fitted,
                                                     tmp_path):
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        mdir = self._saved(fitted, tmp_path)
        eng = ServingEngine(WorkflowModel.load(mdir), buckets=[1, 8, 16])
        assert any("bucket ladder" in p for p in eng.manifest_mismatch)

    def test_monitor_profile_change_is_flagged(self, fitted, tmp_path):
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        mdir = self._saved(fitted, tmp_path)
        mon = os.path.join(mdir, "monitor.json")
        if os.path.exists(mon):
            os.remove(mon)  # profile vanished since the stamp
        else:
            with open(mon, "w") as f:
                json.dump({"features": []}, f)  # profile appeared
        eng = ServingEngine(WorkflowModel.load(mdir))
        assert any("monitor.json" in p for p in eng.manifest_mismatch)


class TestPrewarmManifestAndPersistentCache:
    def test_manifest_roundtrip(self, fitted, tmp_path):
        model, _, _ = fitted
        mdir = str(tmp_path / "model")
        model.save(mdir)
        from transmogrifai_tpu.workflow.workflow import WorkflowModel
        m2 = WorkflowModel.load(mdir)
        assert m2.source_path == mdir
        eng = ServingEngine(m2, buckets=[1, 4])
        assert eng.write_manifest() == os.path.join(mdir, "serve.json")
        # a fresh engine over the same dir adopts the manifest ladder
        eng2 = ServingEngine(WorkflowModel.load(mdir))
        assert eng2.buckets == (1, 4)
        # corrupt manifest: startup must not crash, defaults win
        with open(os.path.join(mdir, "serve.json"), "w") as f:
            f.write("{broken")
        eng3 = ServingEngine(WorkflowModel.load(mdir), max_batch=8)
        assert eng3.buckets == (1, 8)

    def test_prewarm_only_then_fresh_process_zero_compiles(self, fitted,
                                                           tmp_path):
        """THE deploy-time acceptance pin: `serve --prewarm-only`
        populates the persistent compilation cache; a fresh process
        serving the same artifact performs 0 true XLA compiles — every
        bucket executable is a cache hit."""
        model, _, _ = fitted
        mdir = str(tmp_path / "model")
        model.save(mdir)
        cache = str(tmp_path / "xla-cache")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   TMOG_COMPILE_CACHE_DIR=cache)
        env.pop("PYTHONSTARTUP", None)
        r1 = subprocess.run(
            [sys.executable, "-m", "transmogrifai_tpu", "serve", mdir,
             "--prewarm-only", "--max-batch", "8"],
            env=env, capture_output=True, text=True, timeout=300)
        assert r1.returncode == 0, r1.stderr[-2000:]
        doc = json.loads(r1.stdout.strip().splitlines()[-1])
        assert doc["prewarm"]["buckets"] == [1, 8]
        assert doc["prewarm"]["manifest"] == os.path.join(mdir,
                                                          "serve.json")
        assert os.listdir(cache), "prewarm populated no cache entries"
        probe = (
            "import os\n"
            "from transmogrifai_tpu.utils.metrics import collector\n"
            "from transmogrifai_tpu.utils import tracing\n"
            "from transmogrifai_tpu.serve import ServingEngine\n"
            "collector.enable('probe')\n"
            f"eng = ServingEngine({mdir!r})\n"
            "s = eng.prewarm()\n"
            "assert eng.buckets == (1, 8), eng.buckets  # manifest ladder\n"
            "print('TRUE_COMPILES=%d CACHE_HITS=%d'\n"
            "      % (tracing.tracker.true_compiles,\n"
            "         tracing.tracker.total_cache_hits))\n"
        )
        r2 = subprocess.run([sys.executable, "-c", probe], env=env,
                            capture_output=True, text=True, timeout=300)
        assert r2.returncode == 0, r2.stderr[-2000:]
        line = [l for l in r2.stdout.splitlines()
                if l.startswith("TRUE_COMPILES=")][0]
        true_c = int(line.split()[0].split("=")[1])
        hits = int(line.split()[1].split("=")[1])
        assert true_c == 0, f"fresh-process prewarm compiled: {line}"
        # the jitted math stages really exist AND all loaded from cache
        assert hits > 0, line
