"""Headline benchmark: ModelSelector CV sweep wall-clock.

The reference's north-star workload (BASELINE.json): a
BinaryClassificationModelSelector sweep — folds x hyperparameter-grid
logistic fits + AuPR scoring — over an HBM-resident feature matrix
(reference inner loop: core/.../impl/tuning/OpValidator.scala:270-312, one
Spark fit per (model, grid, fold) on 8 driver threads).

Here the whole sweep is ONE XLA program (vmap over folds x grid, Newton
solves on the MXU). The baseline stand-in is the same sweep, fit
sequentially with host-BLAS numpy on a row subsample and scaled to full
size — an optimistic proxy for the reference's Spark-local path (which adds
JVM/DataFrame overhead on top of BLAS).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_ROWS = 1_000_000
N_COLS = 64
FOLDS = 5
GRID = 16
CPU_FALLBACK_ROWS = 100_000  # reduced size when the TPU tunnel is down
BASELINE_SUB = 50_000  # numpy baseline row subsample (scaled up linearly)
NEWTON_ITERS = 15
PROBE_TIMEOUT_S = 90  # first TPU backend init can be slow; hang = tunnel down


def probe_backend(timeout=PROBE_TIMEOUT_S, retries=1):
    """Initialize the jax backend in a SUBPROCESS with a hard timeout.

    Round-1 failure mode: this environment's sitecustomize dials a TPU
    tunnel on first backend init; when the tunnel is down, init either hangs
    forever (MULTICHIP_r01 rc=124) or raises (BENCH_r01 rc=1). Probing in a
    killable child process means the bench itself can never hang, and a
    failed probe downgrades to the CPU backend instead of producing nothing.
    """
    code = "import jax; print('BACKEND=' + jax.default_backend())"
    for _ in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            continue
        if r.returncode == 0:
            for line in r.stdout.splitlines():
                if line.startswith("BACKEND="):
                    return line.split("=", 1)[1]
    return None


def make_data(n, d, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = (rng.normal(size=d) / np.sqrt(d)).astype(np.float32)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float32)
    fold = rng.integers(0, FOLDS, size=n)
    masks = np.stack([(fold != k).astype(np.float32) for k in range(FOLDS)])
    regs = np.logspace(-4, -0.5, GRID).astype(np.float32)
    return X, y, masks, regs


def device_sweep_seconds(X, y, masks, regs):
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.ops.glm import fit_logistic
    from transmogrifai_tpu.ops import metrics_ops as M

    @jax.jit
    def sweep(X, y, masks, regs):
        w = jnp.ones(X.shape[0], jnp.float32)

        def one(mask, reg):
            beta, b0 = fit_logistic(X, y, mask * w, reg, 0.0)
            score = X @ beta + b0
            return M.au_pr(score, y, (1.0 - mask) * w)

        return jax.vmap(lambda m: jax.vmap(lambda r: one(m, r))(regs))(masks)

    Xd, yd, md, rd = map(jax.device_put, (X, y, masks, regs))
    # NB: time to host materialization, not block_until_ready — under remote
    # device tunnels readiness can resolve before execution completes; the
    # [FOLDS, GRID] result is tiny so the readback adds only RPC latency
    np.asarray(sweep(Xd, yd, md, rd))  # compile + warm
    t0 = time.perf_counter()
    out = np.asarray(sweep(Xd, yd, md, rd))
    dt = time.perf_counter() - t0
    aupr = float(out.mean(axis=0).max())
    return dt, aupr


def numpy_fit_logistic(X, y, w, reg, iters=NEWTON_ITERS):
    n, d = X.shape
    beta = np.zeros(d, np.float64)
    b0 = 0.0
    Xw = X.astype(np.float64)
    for _ in range(iters):
        m = Xw @ beta + b0
        p = 1 / (1 + np.exp(-m))
        g = w * (p - y)
        h = np.maximum(w * p * (1 - p), 1e-6)
        Xh = Xw * h[:, None]
        H = Xw.T @ Xh + reg * np.sum(w) * np.eye(d)
        gb = Xw.T @ g + reg * np.sum(w) * beta
        beta -= np.linalg.solve(H, gb)
        b0 -= g.sum() / h.sum()
    return beta, b0


def numpy_au_pr(score, y, w):
    order = np.argsort(-score)
    y, w = y[order], w[order]
    tp = np.cumsum(w * y)
    fp = np.cumsum(w * (1 - y))
    prec = tp / np.maximum(tp + fp, 1e-12)
    rec = tp / max(tp[-1], 1e-12)
    return float(np.trapezoid(prec, rec) if hasattr(np, "trapezoid")
                 else np.trapz(prec, rec))


def baseline_sweep_seconds(X, y, masks, regs):
    """Sequential numpy sweep on a subsample, scaled to N_ROWS."""
    n_sub = min(BASELINE_SUB, X.shape[0])
    Xs, ys = X[:n_sub], y[:n_sub]
    ms = masks[:, :n_sub]
    t0 = time.perf_counter()
    for k in range(FOLDS):
        w = ms[k]
        for reg in regs:
            beta, b0 = numpy_fit_logistic(Xs, ys, w, float(reg))
            numpy_au_pr(Xs @ beta + b0, ys, 1.0 - w)
    dt = time.perf_counter() - t0
    return dt * (X.shape[0] / n_sub)


def main():
    backend = probe_backend()
    error = None
    n_rows = N_ROWS
    if backend is None or backend == "cpu":
        # TPU tunnel down (or image has no accelerator): run the same sweep
        # on the CPU backend at reduced size so a perf number is ALWAYS
        # recorded. Forcing the platform before first backend init avoids
        # the hanging axon dial entirely.
        from transmogrifai_tpu.utils.platform import force_cpu

        force_cpu(1)
        if backend is None:
            error = "tpu backend unreachable; cpu fallback at reduced size"
        backend = "cpu"
        n_rows = CPU_FALLBACK_ROWS

    X, y, masks, regs = make_data(n_rows, N_COLS)
    dev_s, aupr = device_sweep_seconds(X, y, masks, regs)
    base_s = baseline_sweep_seconds(X, y, masks, regs)
    out = {
        "metric": f"cv_sweep_{n_rows//1000}k_rows_{FOLDS}x{GRID}_wall",
        "value": round(dev_s, 4),
        "unit": "s",
        "vs_baseline": round(base_s / dev_s, 2),
        "backend": backend,
        "au_pr": round(aupr, 4),
    }
    if error:
        out["error"] = error
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never exit without a parseable JSON line
        print(json.dumps({
            "metric": "cv_sweep_wall", "value": -1.0, "unit": "s",
            "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}"}))
        sys.exit(0)  # the error field conveys failure; keep rc parseable-green
