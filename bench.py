"""Headline benchmark: the BASELINE.json workloads, measured end to end.

North-star (BASELINE.json config 5): a BinaryClassificationModelSelector
sweep — 5-fold CV x 64 model configurations (48 logistic-regression grid
points + 16 XGBoost-style histogram-GBT configs) over a 10M x 64 feature
matrix. Reference inner loop: core/.../impl/tuning/OpValidator.scala:270-312
(one Spark fit per (model, grid, fold) on 8 driver threads).

Device path = the framework's own validator: the GLM grid runs as chunked
vmapped XLA programs (bf16 X, f32 solver state), trees run mask-fold fits
against a once-binned matrix. The host baseline is MEASURED at the full row
count (per-config cost x config count — configs within a family are
cost-identical by construction), not extrapolated from a subsample; numpy's
multithreaded BLAS makes it a GENEROUS stand-in for the reference's
Spark-local path (which adds JVM/DataFrame overhead on top of the same
BLAS). vs_baseline_8thread additionally divides by the reference's
8-thread pool for the most conservative comparison.

Also measured: MFU from XLA's own cost analysis, an AuPR parity delta
between the device sweep winner and the same config fit on host, the
wide-transmogrify config (vectorized host transforms vs a reference-shaped
per-row loop), and the three helloworld example flows.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
A watchdog emits the partial JSON if the time budget expires mid-phase.
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2400"))
PROBE_TIMEOUT_S = 90

TPU_CFG = dict(n_rows=10_000_000, n_cols=64, folds=5, glm_grid=48,
               gbt_grid=16, gbt_rounds=10, gbt_depth=6, gbt_bins=32,
               wide_rows=1_000_000)
# CPU fallback records liveness when the TPU tunnel is down, not a perf
# claim — sized so the whole bench finishes in a few minutes
CPU_CFG = dict(n_rows=200_000, n_cols=64, folds=5, glm_grid=12,
               gbt_grid=4, gbt_rounds=5, gbt_depth=4, gbt_bins=32,
               wide_rows=60_000)

# peak bf16 TFLOP/s by device kind substring (ordered: most specific first)
PEAK_BF16 = [("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
             ("v4", 275e12), ("v3", 123e12), ("v2", 46e12)]

RESULT: dict = {"metric": "cv_sweep_wall", "value": -1.0, "unit": "s",
                "vs_baseline": 0.0}
_T0 = time.time()

# Incremental persistence: every completed phase snapshots RESULT to disk,
# so a dying TPU tunnel / killed process can no longer erase the evidence
# already gathered (round-2 failure mode: the recorded artifact was a CPU
# fallback because the tunnel died mid-run and took the session's TPU
# numbers with it).
PARTIAL_PATH = os.environ.get(
    "BENCH_PARTIAL_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_partial.json"))


def persist_partial(phase: str) -> None:
    try:
        RESULT["last_phase"] = phase
        with open(PARTIAL_PATH + ".tmp", "w") as f:
            json.dump(RESULT, f)
        os.replace(PARTIAL_PATH + ".tmp", PARTIAL_PATH)
    except OSError:
        pass


TRACE_DIR = os.environ.get("BENCH_TRACE_DIR")


def save_trace_artifacts() -> None:
    """Flush the BENCH_TRACE_DIR span tree to disk. Called from the
    happy path AND the budget-alarm/fatal paths: the preempted long run
    is exactly the run the trace exists to make inspectable, so dying
    must not lose it (events.jsonl already streamed)."""
    if not TRACE_DIR:
        return
    try:
        from transmogrifai_tpu.utils.metrics import collector
        if not collector.enabled:
            return
        collector.save(os.path.join(TRACE_DIR, "bench_stage_metrics.json"))
        collector.save_chrome_trace(
            os.path.join(TRACE_DIR, "bench_trace.json"))
    except Exception:
        pass  # best-effort: never block the JSON emit on trace IO


def emit_and_exit(signum=None, frame=None):
    RESULT.setdefault("errors", []).append("time budget expired; partial run")
    persist_partial("budget_expired")
    save_trace_artifacts()
    print(json.dumps(RESULT), flush=True)
    os._exit(0)


def remaining() -> float:
    return BUDGET_S - (time.time() - _T0)


def log(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:6.1f}s] {msg}", file=sys.stderr,
          flush=True)


def probe_backend(timeout=PROBE_TIMEOUT_S, retries=1):
    """Initialize the jax backend in a SUBPROCESS with a hard timeout.

    Round-1 failure mode: this environment's sitecustomize dials a TPU
    tunnel on first backend init; when the tunnel is down, init either
    hangs forever (MULTICHIP_r01 rc=124) or raises (BENCH_r01 rc=1).
    Probing in a killable child means the bench itself can never hang, and
    a failed probe downgrades to the CPU backend instead of producing
    nothing.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu", "cpu"  # caller pinned the platform; nothing to probe
    code = ("import jax; d=jax.devices()[0]; "
            "print('BACKEND='+jax.default_backend()+'|'+d.device_kind)")
    for _ in range(retries + 1):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired:
            continue
        if r.returncode == 0:
            for line in r.stdout.splitlines():
                if line.startswith("BACKEND="):
                    backend, _, kind = line[8:].partition("|")
                    return backend, kind
    return None, ""


# -- data -------------------------------------------------------------------

def truth_beta(d):
    """Ground-truth coefficients shared by the device draw and the host
    twin, so both fits chase the SAME population optimum (the AuPR parity
    probe depends on this)."""
    rng = np.random.default_rng(123)
    return (rng.normal(size=d) / np.sqrt(d)).astype(np.float32)


def make_data(n, d, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    logits = X @ truth_beta(d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def device_data(n, d, folds, dtype):
    """Generate the sweep data ON DEVICE (one XLA program) — over a remote
    TPU tunnel this avoids shipping a multi-GB host matrix through the
    wire; the host baseline uses an independently drawn twin of the same
    distribution (its cost is data-independent: fixed-iteration solvers).
    Same key + static dtype means X can be regenerated bit-identically in
    another precision later."""
    import jax
    import jax.numpy as jnp

    beta_np = truth_beta(d)

    def gen(key):
        kx, _, ku, kf = jax.random.split(key, 4)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        p = jax.nn.sigmoid(X @ jnp.asarray(beta_np))
        y = (jax.random.uniform(ku, (n,)) < p).astype(jnp.float32)
        fold = jax.random.randint(kf, (n,), 0, folds)
        masks = (fold[None, :]
                 != jnp.arange(folds)[:, None]).astype(jnp.float32)
        return X.astype(dtype), y, masks

    X, y, masks = jax.jit(gen)(jax.random.PRNGKey(0))
    jax.block_until_ready((X, y, masks))
    return X, y, masks


def glm_grids(g):
    regs = np.logspace(-4, -0.5, max(g // 3, 1))
    out = [{"reg_param": float(r), "elastic_net_param": a}
           for r in regs for a in (0.0, 0.25, 0.5)]
    return out[:g]


def gbt_grids(cfg):
    out = [{"num_round": cfg["gbt_rounds"], "max_depth": d, "eta": e,
            "reg_lambda": l, "max_bins": cfg["gbt_bins"]}
           for d in (cfg["gbt_depth"] - 2, cfg["gbt_depth"])
           for e in (0.05, 0.1, 0.2, 0.3) for l in (1.0, 5.0)]
    return out[:cfg["gbt_grid"]]


# -- device sweeps (the framework's own validator paths) --------------------

def device_sweeps(X, y, cfg, sweep_dtype, errors):
    """GLM + tree sweeps through the framework validator. Each family is
    independently fault-isolated: a failure (e.g. an OOM on untested
    hardware) records an error and zeroes that family instead of erasing
    the whole headline metric."""
    import jax.numpy as jnp
    from transmogrifai_tpu.automl.tuning.validators import CrossValidation
    from transmogrifai_tpu.evaluators.evaluators import Evaluators
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.models.trees import OpXGBoostClassifier

    import transmogrifai_tpu.automl.tuning.validators as V

    ev = Evaluators.BinaryClassification.au_pr()
    val = CrossValidation(ev, num_folds=cfg["folds"], seed=42,
                          sweep_dtype=sweep_dtype)
    # synthetic standard-normal features: standardization is a statistical
    # no-op; skipping it avoids a per-lane [n, d] standardized copy
    lr = OpLogisticRegression(max_iter=15, standardization=False)
    ggrids = glm_grids(cfg["glm_grid"])
    tgrids = gbt_grids(cfg)

    best_glm = best_tree = None
    glm_s = tree_s = 0.0
    glm_warm_s = None
    glm_route = None
    glm_info = None  # round/pass telemetry of the streamed route
    saved_min_rows = V.STREAMED_SWEEP_MIN_ROWS
    log(f"GLM sweep: {len(ggrids)} grids x {cfg['folds']} folds")
    try:
        try:
            t0 = time.perf_counter()
            best_glm = val.validate([(lr, [dict(g) for g in ggrids])], X, y)
            # every validate() route np.asarray()s its fold metrics to
            # host floats before returning, so this wall is device-synced
            # tmoglint: disable=TPU005  validate() blocks via np.asarray
            glm_s = time.perf_counter() - t0
            glm_route = best_glm.validated[0].route
            glm_info = val.last_streamed_telemetry
            log(f"GLM sweep done in {glm_s:.2f}s (incl. compile, "
                f"route={glm_route}, telemetry={glm_info})")
        except Exception as e:
            errors.append(f"glm sweep: {type(e).__name__}: {str(e)[:200]}")
            # the streamed lane-batched kernel is the newest code on this
            # hardware — retry once through the battle-tested vmapped route
            # rather than losing the headline family (round 1 recorded no
            # perf number at all; never again). The override stays in
            # place through the warm re-run below so the warm timing runs
            # the SAME route as the cold one it is compared against;
            # restored in the outer finally. The guard resolves the row
            # floor the way the validator did (planner crossover unless
            # the module global was hand-reassigned) — the raw global
            # would miss a planner-lowered floor and skip the retry.
            streamed_floor = V.STREAMED_SWEEP_MIN_ROWS
            if streamed_floor == V._STREAMED_SWEEP_MIN_ROWS_HAND:
                try:
                    from transmogrifai_tpu.planner.plan import \
                        glm_streamed_min_rows
                    streamed_floor = glm_streamed_min_rows(
                        cfg["n_cols"], cfg["folds"] * cfg["glm_grid"])
                except Exception:
                    pass
            if streamed_floor <= cfg["n_rows"]:
                try:
                    V.STREAMED_SWEEP_MIN_ROWS = 10 ** 15
                    log("retrying GLM sweep on the vmapped route")
                    t0 = time.perf_counter()
                    best_glm = val.validate([(lr, [dict(g) for g in ggrids])],
                                            X, y)
                    # tmoglint: disable=TPU005  validate blocks via np.asarray
                    glm_s = time.perf_counter() - t0
                    glm_route = best_glm.validated[0].route
                    glm_info = None  # streamed telemetry does not apply
                    errors.append("glm sweep ok on vmapped-route retry")
                    log(f"GLM sweep (vmapped) done in {glm_s:.2f}s")
                except Exception as e2:
                    errors.append(f"glm sweep retry: {type(e2).__name__}: "
                                  f"{str(e2)[:200]}")
        if best_glm is not None:
            # steady state: the re-run hits the jit cache, isolating XLA
            # compile time (reported separately; the headline keeps cold).
            # Own try/except: a warm-only failure must not read as the GLM
            # family failing — the cold result above already stands.
            try:
                t0 = time.perf_counter()
                val.validate([(lr, [dict(g) for g in ggrids])], X, y)
                # tmoglint: disable=TPU005  validate blocks via np.asarray
                glm_warm_s = time.perf_counter() - t0
                log(f"GLM sweep warm: {glm_warm_s:.2f}s")
            except Exception as e:
                errors.append(f"glm warm rerun: {type(e).__name__}: "
                              f"{str(e)[:200]}")
    finally:
        V.STREAMED_SWEEP_MIN_ROWS = saved_min_rows

    from transmogrifai_tpu.utils.metrics import collector as _mc
    log(f"tree sweep: {len(tgrids)} configs x {cfg['folds']} folds")
    # On TPU the tree family runs in a KILLABLE subprocess: round-3 first
    # contact saw fit_gbt HANG (not raise) inside the pallas path for 14+
    # minutes — an in-process hang is unkillable (blocked RPC) and eats
    # the whole bench budget with nothing recorded. The child regenerates
    # the same device data (deterministic gen), so nothing is shipped.
    try:
        import jax
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    in_process = not (on_tpu
                      and os.environ.get("BENCH_TREE_SUBPROC", "1") != "0")
    if not in_process:
        best_tree, tree_s, child_ran = _tree_sweep_subprocess(cfg, errors)
        # single-tenant runtime: the child never got the device — the
        # in-process path below is the one that works there
        in_process = best_tree is None and not child_ran
    kernel_roofline = []
    if in_process:
        # a BENCH_TRACE_DIR run already enabled the collector in main();
        # re-enabling here would reset its span tree mid-run
        mc_was_enabled = _mc.enabled
        try:
            # stage-metric collection ON so the fused tree fits record
            # per-kernel roofline spans (achieved GB/s vs the HBM roof)
            if not mc_was_enabled:
                _mc.enable("bench_tree_sweep")
            t0 = time.perf_counter()
            best_tree = val.validate([(OpXGBoostClassifier(),
                                       [dict(g) for g in tgrids])], X, y)
            # tmoglint: disable=TPU005  validate blocks via np.asarray
            tree_s = time.perf_counter() - t0
            kernel_roofline = [k.to_json()
                               for k in _mc.current.kernel_metrics]
            harvest_spans_to_corpus("bench_tree_sweep")
            if not mc_was_enabled:
                _mc.disable()
            log(f"tree sweep done in {tree_s:.2f}s")
        except Exception as e:
            if not mc_was_enabled:
                _mc.disable()
            errors.append(f"tree sweep: {type(e).__name__}: {str(e)[:200]}")
            # a Mosaic/pallas compile failure surfaces as an exception —
            # retry once on the XLA-only path rather than losing the family
            from transmogrifai_tpu.ops import pallas_hist, trees as Tmod
            if pallas_hist.available():
                try:
                    Tmod.set_pallas_enabled(False)
                    log("retrying tree sweep without pallas")
                    t0 = time.perf_counter()
                    best_tree = val.validate(
                        [(OpXGBoostClassifier(),
                          [dict(g) for g in tgrids])], X, y)
                    # tmoglint: disable=TPU005  validate blocks via np.asarray
                    tree_s = time.perf_counter() - t0
                    errors.append("tree sweep ok on retry without pallas")
                    log(f"tree sweep (no pallas) done in {tree_s:.2f}s")
                except Exception as e2:
                    errors.append(f"tree sweep retry: {type(e2).__name__}: "
                                  f"{str(e2)[:200]}")

    candidates = [b for b in (best_glm, best_tree) if b is not None]
    if not candidates:
        raise RuntimeError("both sweep families failed: " + "; ".join(errors))
    best = max(candidates, key=lambda b: b.best_metric)
    # the route label must come from the process where the fits ran: a
    # child may have disabled pallas after a Mosaic failure or run with
    # different flags than the parent
    tree_route = getattr(best_tree, "tree_route", None) or \
        (tree_route_label(cfg) if best_tree is not None else None)
    out = dict(glm_s=glm_s, tree_s=tree_s, glm_route=glm_route,
               tree_route=tree_route,
               glm_fits=len(ggrids) * cfg["folds"] if best_glm else 0,
               tree_fits=len(tgrids) * cfg["folds"] if best_tree else 0,
               best_name=best.name, best_grid=best.best_grid,
               best_au_pr=float(best.best_metric))
    if glm_route == "streamed" and glm_info:
        # convergence telemetry: the executed-FLOP model and the
        # acceptance gates read these (monotone active-lane shrink,
        # one-pass squared sweeps). The legacy "global" kernel has no
        # round counters — emit only the keys that exist rather than
        # JSON nulls that break numeric consumers.
        out["glm_telemetry"] = glm_info
        for k in ("glm_rounds", "lanes_retired", "data_passes"):
            if glm_info.get(k) is not None:
                out[k] = glm_info[k]
    kernel_roofline = kernel_roofline or \
        getattr(best_tree, "kernel_roofline", None) or []
    if kernel_roofline:
        out["kernel_roofline"] = kernel_roofline
    if best_tree is not None:
        # TMOG_TREE_SCAN A/B marker + the compile-wall proxy it moves:
        # artifacts from scan-on and scan-off runs stay attributable.
        # Like tree_route, the child's own values win when the sweep ran
        # in a subprocess (its flags/spans are the ones that fitted)
        from transmogrifai_tpu.ops import trees as _T
        child_scan = getattr(best_tree, "tree_scan", None)
        out["tree_scan"] = bool(_T.tree_scan_enabled()) \
            if child_scan is None else bool(child_scan)
        tts = getattr(best_tree, "tree_trace_s", None)
        if tts is None:
            tts = tree_trace_seconds(kernel_roofline)
        if tts:
            out["tree_trace_s"] = tts
    child_flops = getattr(best_tree, "fit_flops", 0.0)
    if child_flops:
        out["tree_fit_flops"] = child_flops
    if glm_warm_s is not None:
        out["glm_warm_s"] = round(glm_warm_s, 3)
    return out


def tree_trace_seconds(kernel_roofline):
    """Cold-minus-warm compile proxy from the tree sweep's own roofline
    spans: a cold span's wall includes jit trace + Mosaic compile, so
    subtracting the median warm wall of the same kernel label leaves the
    trace+compile share. Labels with no warm twin contribute their full
    cold wall (an upper bound). This is the number the level-scan rewrite
    attacks — O(1) programs in depth — so BENCH JSON carries it as
    `tree_trace_s` next to the `tree_scan` flag for TMOG_TREE_SCAN A/B
    runs (docs/performance.md). Spans group by (kernel, bytes_hbm):
    analytic bytes are a pure function of the program shape (rows,
    lanes, depth, rounds, itemsize), so a grid sweep whose chunking
    emits several lane counts under one label never mixes one shape's
    warm walls into another shape's cold baseline."""
    by = {}
    for k in kernel_roofline or []:
        by.setdefault((k.get("kernel"), k.get("bytes_hbm")), []).append(k)
    total = 0.0
    for spans in by.values():
        colds = [float(s.get("wall_seconds", 0.0)) for s in spans
                 if s.get("cold")]
        warms = sorted(float(s.get("wall_seconds", 0.0)) for s in spans
                       if not s.get("cold"))
        if not colds:
            continue
        warm_med = warms[len(warms) // 2] if warms else 0.0
        total += sum(max(c - warm_med, 0.0) for c in colds)
    return round(total, 3)


def tree_route_label(cfg):
    """Which tree kernel path the mask-fold sweep at cfg's row count
    takes, read from the flags IN THIS PROCESS — call it where the fits
    ran (the child computes its own label; the parent must not infer one
    across the process boundary)."""
    import jax
    if jax.default_backend() != "tpu":
        return "host_native_or_xla"
    from transmogrifai_tpu.ops import pallas_hist as ph
    from transmogrifai_tpu.models.trees import _TreeEstimator
    if cfg["n_rows"] <= _TreeEstimator._VMAP_FOLD_MAX_ROWS:
        return "xla_fold_vmap"
    if not ph.available():
        return "xla_matmul"
    return "fused_bf16" if ph._HIST_BF16 else "fused_f32"


class _TreeSweepResult:
    """Duck-typed stand-in for the validator's BestEstimator when the tree
    sweep ran in a child process (only the fields device_sweeps reads)."""

    def __init__(self, name, best_grid, best_metric, fit_flops=0.0,
                 tree_route=None, kernel_roofline=None, tree_scan=None,
                 tree_trace_s=None):
        self.tree_route = tree_route
        self.name = name
        self.best_grid = best_grid
        self.best_metric = best_metric
        self.fit_flops = fit_flops
        self.kernel_roofline = kernel_roofline or []
        self.tree_scan = tree_scan
        self.tree_trace_s = tree_trace_s


def tree_sweep_child(cfg):
    """Child-process body (--tree-sweep): regenerate the device data and
    run the tree family through the validator; one JSON line out."""
    import jax.numpy as jnp
    from transmogrifai_tpu.automl.tuning.validators import CrossValidation
    from transmogrifai_tpu.evaluators.evaluators import Evaluators
    from transmogrifai_tpu.models.trees import OpXGBoostClassifier

    dtype = jnp.bfloat16 if os.environ.get("BENCH_TREE_DTYPE",
                                           "bf16") == "bf16" else jnp.float32
    X, y, _ = device_data(cfg["n_rows"], cfg["n_cols"], cfg["folds"], dtype)
    val = CrossValidation(Evaluators.BinaryClassification.au_pr(),
                          num_folds=cfg["folds"], seed=42, sweep_dtype=dtype)
    tgrids = gbt_grids(cfg)
    from transmogrifai_tpu.utils.metrics import collector
    collector.enable("bench_tree_sweep_child")
    t0 = time.perf_counter()
    best = val.validate([(OpXGBoostClassifier(),
                          [dict(g) for g in tgrids])], X, y)
    # tmoglint: disable=TPU005  validate() blocks via np.asarray
    dt = time.perf_counter() - t0
    kernel_roofline = [k.to_json() for k in collector.current.kernel_metrics]
    harvest_spans_to_corpus("bench_tree_sweep_child")
    collector.disable()
    from transmogrifai_tpu.ops import pallas_hist
    # per-fit FLOPs from XLA cost analysis, here where the jit cache is
    # warm (the parent would re-lower — and re-risk a pallas compile hang)
    flops = tree_flops_cost_analysis(cfg, dtype)
    from transmogrifai_tpu.ops import trees as _T
    print("TREE|" + json.dumps(dict(
        tree_s=round(dt, 3), name=best.name, best_grid=best.best_grid,
        best_metric=float(best.best_metric), fit_flops=flops,
        pallas=pallas_hist.available(),
        kernel_roofline=kernel_roofline,
        tree_scan=bool(_T.tree_scan_enabled()),
        tree_trace_s=tree_trace_seconds(kernel_roofline),
        tree_route=tree_route_label(cfg))), flush=True)


def _tree_sweep_subprocess(cfg, errors, timeout_s=None):
    """Run the tree family in a killable child; on hang/crash retry once
    with pallas disabled. Returns (result_or_None, tree_s, child_ran):
    child_ran=False means no child even initialized a backend (e.g. a
    single-tenant libtpu refusing a second process) and the caller should
    fall back to the in-process path."""
    if timeout_s is None:
        timeout_s = min(max(remaining() * 0.5, 300), 1200)
    attempts = [("pallas", {}), ("no_pallas", {"TMOG_NO_PALLAS": "1"})]
    from transmogrifai_tpu.ops import pallas_hist
    if not pallas_hist.enabled():
        attempts = attempts[1:]
    child_ran = False
    for tag, extra_env in attempts:
        # the child timeout must fire well before the parent's SIGALRM
        # (BUDGET_S-30): an orphaned child would keep the device busy
        # after the parent reports
        budget = min(timeout_s, remaining() - 90)
        if budget < 240:
            errors.append(f"tree sweep ({tag}) skipped: budget")
            # not a single-tenant signal: the caller must NOT fall back to
            # the unkillable in-process path with this little budget left
            child_ran = True
            break
        env = dict(os.environ)
        env.update(extra_env)
        env["BENCH_TREE_CFG"] = json.dumps(cfg)  # child runs THIS config
        log(f"tree sweep child ({tag}), timeout {budget:.0f}s")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--tree-sweep"],
                capture_output=True, text=True, timeout=budget, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            child_ran = True  # it got far enough to hang on real work
            errors.append(f"tree sweep ({tag}): HANG killed at {budget:.0f}s")
            continue
        for line in (r.stdout or "").splitlines():
            if line.startswith("TREE|"):
                d = json.loads(line[5:])
                if tag == "no_pallas":
                    errors.append("tree sweep ok on no-pallas child retry")
                log(f"tree sweep child ({tag}) done in {d['tree_s']}s")
                return (_TreeSweepResult(d["name"], d["best_grid"],
                                         d["best_metric"],
                                         d.get("fit_flops", 0.0),
                                         d.get("tree_route"),
                                         d.get("kernel_roofline"),
                                         d.get("tree_scan"),
                                         d.get("tree_trace_s")),
                        d["tree_s"], True)
        stderr = (r.stderr or "").strip()
        # device-contention init failure: the runtime is single-tenant,
        # so stop burning attempts and let the caller run in-process
        if "already in use" in stderr.lower() or \
                "unable to initialize backend" in stderr.lower():
            errors.append(f"tree sweep child ({tag}): device single-tenant; "
                          "falling back in-process")
            return None, 0.0, False
        child_ran = True
        errors.append(f"tree sweep ({tag}): rc={r.returncode} "
                      f"{stderr[-200:]}")
    return None, 0.0, child_ran


def glm_flops_estimate(cfg, route, telemetry=None):
    """Executed FLOPs for the GLM sweep, matched to the route that actually
    ran (ADVICE r2: attributing vmapped timings to the streamed FLOP model
    misstates MFU) AND to the convergence telemetry the sweep recorded.

    Streamed (ops/glm_sweep.py): per executed lane-pass — eta 2nd +
    gradient 2nd + FULL symmetric per-lane Gram einsum 2nd^2. (The old
    model billed the compressed-triangle Gram 2nT, T = d(d+1)/2, which the
    kernel retired when the triangle's column gather proved to be the TPU
    wall — _hessian_blocks moved to the full einsum — and it hard-coded 15
    iterations.) Executed lane-passes come from the sweep's own telemetry
    — `padded_lane_passes` (sum over rounds of bucket_size x iterations:
    the device runs the padded power-of-two bucket, so that is what MFU
    must bill; `lane_passes` is the USEFUL active-lane work) with the
    logical count as fallback; folds for the one-pass squared-loss Gram
    path. `glm_rounds`/`lanes_retired`/`data_passes` land in the sweep
    JSON alongside. Only when telemetry is absent entirely does it fall
    back to the legacy 15-iterations x all-lanes assumption.

    Vmapped (ops/glm.py per lane): eta 2nd + gradient 2nd + full weighted
    Gram 2nd^2 + the [n, d] scale nd; 15 iterations x lanes."""
    n, d = cfg["n_rows"], cfg["n_cols"]
    fits = cfg["glm_grid"] * cfg["folds"]
    if route == "streamed":
        per_lane_pass = 4 * n * d + 2 * n * d * d
        t = telemetry or {}
        lane_passes = t.get("padded_lane_passes") or t.get("lane_passes")
        if lane_passes:
            return per_lane_pass * lane_passes
        return per_lane_pass * 15 * fits
    # vmapped / sequential per-lane solve
    per_iter_lane = 4 * n * d + 2 * n * d * d + n * d
    return per_iter_lane * 15 * fits


def tree_flops_cost_analysis(cfg, sweep_dtype):
    """Ask XLA itself for the per-fit FLOPs of one GBT config (AOT lowering
    hits the jit cache when shapes match the sweep's)."""
    try:
        import jax
        import jax.numpy as jnp
        from transmogrifai_tpu.ops import trees as T
        n, d = cfg["n_rows"], cfg["n_cols"]
        Xb = jax.ShapeDtypeStruct((n, d), jnp.int32)
        y = jax.ShapeDtypeStruct((n,), jnp.float32)
        w = jax.ShapeDtypeStruct((n,), jnp.float32)
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        # lower the XLA-only variant: custom-call FLOPs are invisible to
        # cost analysis anyway, and a fresh Mosaic compile is the one step
        # that has hung on first hardware contact (round 3)
        pallas_was = T.pallas_enabled()
        T.set_pallas_enabled(False)
        try:
            lowered = T.fit_gbt.lower(
                Xb, y, w, key, n_rounds=cfg["gbt_rounds"],
                depth=cfg["gbt_depth"], n_bins=cfg["gbt_bins"])
            cost = lowered.compile().cost_analysis()
        finally:
            T.set_pallas_enabled(pallas_was)
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception as e:  # cost analysis is best-effort
        log(f"tree cost_analysis unavailable: {e}")
        return 0.0


# -- host baselines (measured at FULL size) ---------------------------------

def numpy_fit_logistic(X, y, w, reg, iters=15):
    """Newton IRLS with f32 BLAS matmuls (f64 d x d solve). f32 sgemm is
    ~2x dgemm throughput, making this baseline FASTER — i.e. the
    vs_baseline ratio more conservative — than the reference's netlib
    path, and halving host RAM at the 10M-row config."""
    n, d = X.shape
    beta = np.zeros(d, np.float32)
    b0 = 0.0
    Xw = np.ascontiguousarray(X, np.float32)
    for _ in range(iters):
        m = Xw @ beta + b0
        p = 1 / (1 + np.exp(-np.clip(m, -30, 30)))
        g = (w * (p - y)).astype(np.float32)
        h = np.maximum(w * p * (1 - p), 1e-6).astype(np.float32)
        Xh = Xw * h[:, None]
        H = (Xw.T @ Xh).astype(np.float64) + reg * np.sum(w) * np.eye(d)
        gb = (Xw.T @ g).astype(np.float64) + reg * np.sum(w) * beta
        beta = (beta - np.linalg.solve(H, gb)).astype(np.float32)
        b0 -= g.sum() / h.sum()
    return beta.astype(np.float64), float(b0)


def numpy_au_pr(score, y, w):
    keep = w > 0
    score, y = score[keep], y[keep]
    order = np.argsort(-score)
    y = y[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    prec = tp / np.maximum(tp + fp, 1e-12)
    rec = tp / max(tp[-1], 1e-12)
    dr = np.diff(rec, prepend=0.0)
    return float((dr * prec).sum())


def baseline_glm(X, y, masks, cfg, n_measure=2):
    """Per-fit cost measured at full rows (configs in the logistic grid are
    cost-identical: same matmuls, fixed iterations); total = cost x fits."""
    w = masks[0]
    times = []
    for i in range(n_measure):
        t0 = time.perf_counter()
        numpy_fit_logistic(X, y, w, 0.01)
        times.append(time.perf_counter() - t0)
        log(f"baseline GLM fit {i}: {times[-1]:.2f}s")
    per_fit = float(np.median(times))
    fits = cfg["glm_grid"] * cfg["folds"]
    return per_fit, per_fit * fits


def numpy_gbt_round(Xb, g, h, depth, n_bins):
    """One boosting round of histogram GBT in numpy (reference-shaped host
    compute): level-wise, per-feature bincount histograms, best-gain split."""
    n, F = Xb.shape
    node = np.zeros(n, np.int32)
    feats = []
    threshs = []
    for lvl in range(depth):
        n_nodes = 1 << lvl
        best_gain = np.full(n_nodes, -np.inf)
        best_f = np.zeros(n_nodes, np.int32)
        best_t = np.zeros(n_nodes, np.int32)
        for f in range(F):
            idx = node * n_bins + Xb[:, f]
            gh = np.bincount(idx, weights=g, minlength=n_nodes * n_bins)
            hh = np.bincount(idx, weights=h, minlength=n_nodes * n_bins)
            gh = gh.reshape(n_nodes, n_bins)
            hh = hh.reshape(n_nodes, n_bins)
            gl = np.cumsum(gh, axis=1)
            hl = np.cumsum(hh, axis=1)
            gt = gl[:, -1:]
            ht = hl[:, -1:]
            gain = (gl ** 2 / np.maximum(hl + 1.0, 1e-6)
                    + (gt - gl) ** 2 / np.maximum(ht - hl + 1.0, 1e-6)
                    - gt ** 2 / np.maximum(ht + 1.0, 1e-6))
            fb = np.argmax(gain, axis=1)
            fg = np.take_along_axis(gain, fb[:, None], 1)[:, 0]
            upd = fg > best_gain
            best_gain = np.where(upd, fg, best_gain)
            best_f = np.where(upd, f, best_f)
            best_t = np.where(upd, fb, best_t)
        feats.append(best_f)
        threshs.append(best_t)
        node = 2 * node + (Xb[np.arange(n), best_f[node]]
                           > best_t[node]).astype(np.int32)
    leaves = 1 << depth
    gl = np.bincount(node, weights=g, minlength=leaves)
    hl = np.bincount(node, weights=h, minlength=leaves)
    return -gl / (hl + 1.0 + 1e-6), node


def baseline_gbt(X, y, masks, cfg):
    """One full boosting ROUND measured at full rows (rounds are
    cost-identical); total = round cost x rounds x configs x folds, plus the
    one-time binning cost per (config, fold)."""
    t0 = time.perf_counter()
    edges = np.quantile(X[:: max(1, len(X) // 200_000)],
                        np.linspace(0, 1, cfg["gbt_bins"] + 1)[1:-1], axis=0)
    Xb = np.empty(X.shape, np.int32)
    for f in range(X.shape[1]):
        Xb[:, f] = np.searchsorted(edges[:, f], X[:, f], side="right")
    bin_s = time.perf_counter() - t0
    log(f"baseline GBT binning: {bin_s:.2f}s")

    w = masks[0]
    margin = np.zeros(len(y), np.float64)
    p = 1 / (1 + np.exp(-margin))
    g = w * (p - y)
    h = np.maximum(w * p * (1 - p), 1e-6)
    t0 = time.perf_counter()
    numpy_gbt_round(Xb, g, h, cfg["gbt_depth"], cfg["gbt_bins"])
    round_s = time.perf_counter() - t0
    log(f"baseline GBT round: {round_s:.2f}s")
    fits = cfg["gbt_grid"] * cfg["folds"]
    total = (round_s * cfg["gbt_rounds"] + bin_s) * fits
    return round_s, total


def aupr_parity(Xh, yh, masks_h, best_grid, Xd, yd):
    """Statistical-parity probe: fit the winning config on device (its own
    10M draw) AND on host (the host twin) with the SAME fold-0 training
    mask as weights, then score the SAME host data with both coefficient
    vectors and compare exact AuPR. Both fits see the same fraction of the
    same distribution, so the betas converge to the same population
    optimum; the delta isolates solver disagreement."""
    from transmogrifai_tpu.models.glm import OpLogisticRegression

    w = masks_h[0]
    reg = float(best_grid.get("reg_param", 0.01))
    alpha = float(best_grid.get("elastic_net_param", 0.0))
    est = OpLogisticRegression(max_iter=15, standardization=False,
                               reg_param=reg, elastic_net_param=alpha)
    model = est.fit_arrays(Xd, yd, w=w)  # device fit, fold-0 train mask
    dev_beta = np.asarray(model.beta, np.float64)
    dev_b0 = float(model.intercept)
    host_beta, host_b0 = numpy_fit_logistic(Xh, yh, w, reg)
    val_w = 1.0 - w
    a_dev = numpy_au_pr(Xh @ dev_beta + dev_b0, yh, val_w)
    a_host = numpy_au_pr(Xh @ host_beta + host_b0, yh, val_w)
    return abs(a_dev - a_host), a_host, a_dev


# -- wide transmogrify ------------------------------------------------------

def make_wide_rows(n, seed=2):
    rng = np.random.default_rng(seed)
    cats_a = [f"cat{i}" for i in range(50)]
    cats_b = [f"seg{i}" for i in range(12)]
    words = ["alpha", "beta", "gamma", "delta", "omega", "sigma", "tau"]
    cols = {
        "plA": rng.choice(cats_a, size=n),
        "plB": rng.choice(cats_b, size=n),
        "txt": np.array([" ".join(rng.choice(words, size=5))
                         for _ in range(n // 100)])[
                             rng.integers(0, max(n // 100, 1), size=n)],
        "r1": rng.normal(size=n),
        "r2": np.where(rng.uniform(size=n) < 0.1, np.nan, rng.normal(size=n)),
        "dt": (1_500_000_000_000
               + rng.integers(0, 10**9, size=n)).astype(np.int64),
        "m1": rng.normal(size=n),  # map keys k0/k1 assembled below
        "m2": rng.normal(size=n),
    }
    return cols


def wide_transmogrify(n):
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.data.dataset import Dataset
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.types import (
        Date, Integral, PickList, Real, RealMap, Text,
    )
    from transmogrifai_tpu.workflow.workflow import Workflow

    cols = make_wide_rows(n)
    maps = np.empty(n, dtype=object)
    for i in range(n):
        maps[i] = {"k0": cols["m1"][i], "k1": cols["m2"][i]}
    ds = Dataset.from_features([
        ("plA", PickList, cols["plA"].tolist()),
        ("plB", PickList, cols["plB"].tolist()),
        ("txt", Text, cols["txt"].tolist()),
        ("r1", Real, cols["r1"].tolist()),
        ("r2", Real, [None if np.isnan(v) else float(v)
                      for v in cols["r2"]]),
        ("dt", Date, cols["dt"].tolist()),
        ("mp", RealMap, list(maps)),
    ])
    feats = [
        FeatureBuilder.PickList("plA").extract(lambda r: r.get("plA")).as_predictor(),
        FeatureBuilder.PickList("plB").extract(lambda r: r.get("plB")).as_predictor(),
        FeatureBuilder.Text("txt").extract(lambda r: r.get("txt")).as_predictor(),
        FeatureBuilder.Real("r1").extract(lambda r: r.get("r1")).as_predictor(),
        FeatureBuilder.Real("r2").extract(lambda r: r.get("r2")).as_predictor(),
        FeatureBuilder.Date("dt").extract(lambda r: r.get("dt")).as_predictor(),
        FeatureBuilder.RealMap("mp").extract(lambda r: r.get("mp")).as_predictor(),
    ]
    vec = transmogrify(feats)
    wf = Workflow().set_input_dataset(ds).set_result_features(vec)
    t0 = time.perf_counter()
    model = wf.train()
    fit_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    scored = model.score(ds)
    score_cold_s = time.perf_counter() - t0
    # serving throughput is a warm-path number: the cold pass pays one-time
    # page-fault/allocator costs for the [n, width] output blocks. Best of
    # 3 passes: single-shot timings on a contended 1-core box swing +-30%
    # (the r2 driver artifact recorded a noise spike as the result).
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        scored = model.score(ds)
        times.append(time.perf_counter() - t0)
    score_s = min(times)
    width = scored.column(vec.name).data.shape[1]

    # reference-shaped baseline: per-row python closure loop (the fused
    # rdd.map of FitStagesUtil.applyOpTransformations:96) producing the
    # SAME output width — 512-dim text hashing, one-hot + null columns,
    # circular date features, per-key map expansion. Measured on the same
    # rows with a time cap; per-row cost is constant so the cap-scale is
    # exact arithmetic, and measured_rows is reported.
    import math
    vocab_a = {c: i for i, c in enumerate(sorted(set(cols["plA"])))}
    vocab_b = {c: i for i, c in enumerate(sorted(set(cols["plB"])))}
    two_pi = 2 * math.pi

    def row_loop_pass(cap):
        t0 = time.perf_counter()
        done = 0
        for i in range(n):
            row = []
            oh = [0.0] * (len(vocab_a) + 2)  # topK + OTHER + null
            oh[vocab_a.get(cols["plA"][i], len(vocab_a))] = 1.0
            row += oh
            oh = [0.0] * (len(vocab_b) + 2)
            oh[vocab_b.get(cols["plB"][i], len(vocab_b))] = 1.0
            row += oh
            toks = cols["txt"][i].lower().split()
            hv = [0.0] * 512  # TransmogrifierDefaults.DefaultNumOfFeatures
            for t in toks:
                hv[hash(t) % 512] += 1.0
            row += hv
            row += [cols["r1"][i], 0.0]
            v = cols["r2"][i]
            isnan = v != v
            row += [0.0 if isnan else v, 1.0 if isnan else 0.0]
            ts = cols["dt"][i] / 86_400_000.0
            for period in (1.0, 7.0, 30.4375, 365.25):
                row += [math.sin(two_pi * ts / period),
                        math.cos(two_pi * ts / period)]
            row += [cols["m1"][i], 0.0, cols["m2"][i], 0.0]
            done = i + 1
            if (i & 1023) == 0 and time.perf_counter() - t0 > cap:
                break
        return (time.perf_counter() - t0) * (n / done), done

    # best of 2 passes, same contention-noise defense as score_s (the
    # baseline must not be inflated by a noise spike either)
    cap = min(120.0, max(remaining() - 60.0, 10.0)) / 2
    (l1, d1), (l2, d2) = row_loop_pass(cap), row_loop_pass(cap)
    loop_s, done = ((l1, d1) if l1 <= l2 else (l2, d2))
    return dict(rows=n, fit_s=round(fit_s, 3), score_s=round(score_s, 3),
                score_cold_s=round(score_cold_s, 3),
                vector_width=int(width),
                rows_per_s=int(n / max(score_s, 1e-9)),
                row_loop_s=round(loop_s, 3),
                row_loop_measured_rows=done,
                vs_row_loop=round(loop_s / max(score_s, 1e-9), 2))


# -- histogram roofline micro-bench (--hist-roofline) -----------------------

def hist_roofline_bench(n_rows=None):
    """Micro-bench for the fused multi-(fold x lane) histogram pipeline:
    analytic bytes-moved per sweep-level for the r5 per-fold baseline vs
    the batched route+hist kernel (one residency of the binned matrix for
    all lanes, count channel derived in VMEM, routing fused into the same
    pass), plus a MEASURED deepest-level pass with achieved GB/s against
    the device's HBM roof. Runs on any backend — on CPU the jnp fallback
    path is what gets timed (a liveness number, not a perf claim); the
    analytic reduction factor is backend-independent. One JSON line."""
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.ops import pallas_hist as PH
    from transmogrifai_tpu.utils.metrics import hbm_roof_gbps, \
        roofline_fields

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    folds, F, n_bins, depth = 5, 64, 32, 6
    B = n_bins + 1
    n = int(n_rows) if n_rows else (10_000_000 if on_tpu else 200_000)
    per_fold = PH.sweep_level_bytes(n, F, folds, fused="per_fold")
    r5 = PH.sweep_level_bytes(n, F, folds, fused="r5")
    fused = PH.sweep_level_bytes(n, F, folds, fused=True)
    out = {"metric": "hist_level_roofline", "backend": backend,
           "n_rows": n, "n_cols": F, "folds": folds, "depth": depth,
           "bytes_per_level_per_fold_route": int(per_fold),
           "bytes_per_level_r5_fold_fused": int(r5),
           "bytes_per_level_fused": int(fused),
           # vs the sequential per-lane route (the fallback when fold
           # fusion is gated off) AND vs what r5's production fold-fused
           # TPU route actually streamed — both, so neither number can
           # be mistaken for the other
           "bytes_reduction_x_vs_per_fold": round(per_fold / fused, 2),
           "bytes_reduction_x_vs_r5_fold_fused": round(r5 / fused, 2)}

    # measured deepest routed level (2^(depth-2) nodes): rep-varying
    # payloads defeat executable result caching on the tunnel, and are
    # PREcomputed so only route_hist sits in the timed window (the +rep
    # shift would otherwise add ~80n bytes of traffic the analytic
    # denominator doesn't count, understating achieved GB/s)
    n_nodes = 1 << (depth - 2)
    key = jax.random.PRNGKey(0)
    kx, kp, kn, kf = jax.random.split(key, 4)
    Xb_t = jax.random.randint(kx, (F, n), 0, B, jnp.int32).astype(jnp.int8)
    pay = jax.random.normal(kp, (2 * folds, n), jnp.float32)
    pays = [pay + float(rep) for rep in range(4)]
    node = jax.random.randint(kn, (folds, n), 0, n_nodes,
                              jnp.int32).astype(jnp.float32)
    f_lvl = jax.random.randint(kf, (folds, n_nodes), 0, F, jnp.int32)
    t_lvl = jnp.full((folds, n_nodes), B // 2, jnp.int32)
    m_lvl = jnp.zeros((folds, n_nodes), jnp.int32)
    jax.block_until_ready((Xb_t, pays, node))

    def one(p):
        return PH.route_hist(Xb_t, p, node, f_lvl, t_lvl, m_lvl,
                             n_nodes=n_nodes, n_bins=B,
                             allow_bf16=True, derive_count=True)

    jax.block_until_ready(one(pays[0]))  # warm/compile
    times = []
    for p in pays[1:]:
        t0 = time.perf_counter()
        jax.block_until_ready(one(p))
        times.append(time.perf_counter() - t0)
    wall = min(times)
    roof = hbm_roof_gbps(jax.devices()[0].device_kind) if on_tpu else None
    rf = roofline_fields(wall, fused, roof)  # shared arithmetic: the
    # micro-bench must report the same numbers a collector.kernel span
    # of the identical pass would
    out.update(level_wall_s=round(wall, 4),
               achieved_gbps=rf["achieved_gbps"])
    if roof:
        out.update(hbm_roof_gbps=rf["roof_gbps"],
                   pct_of_hbm_roof=rf["pct_of_roof"])
    return out


# -- statistics-engine roofline micro-bench (--stats-roofline) --------------

def stats_roofline_bench(n_rows=None):
    """Micro-bench for the one-pass statistics engine (ops/stats_engine):
    analytic bytes-moved and pass counts for the legacy multi-pass
    SanityChecker statistics vs the fused single scan, plus a MEASURED
    fused pass with achieved GB/s against the device's HBM roof. Runs on
    any backend — on CPU the numbers are liveness, not perf claims; the
    pass-count reduction is backend-independent. One JSON line."""
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.ops import stats as S
    from transmogrifai_tpu.ops import stats_engine as SE
    from transmogrifai_tpu.utils.metrics import hbm_roof_gbps, \
        roofline_fields

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    n = int(n_rows) if n_rows else (10_000_000 if on_tpu else 200_000)
    d, n_classes, n_groups = 64, 4, 8
    bytes_pass = SE.stats_pass_bytes(n, d)
    legacy_passes = SE.legacy_pass_count(corr_matrix=True,
                                         n_groups=n_groups)
    out = {"metric": "stats_roofline", "backend": backend,
           "n_rows": n, "n_cols": d, "n_groups": n_groups,
           "bytes_per_pass": int(bytes_pass),
           "legacy_passes": int(legacy_passes), "fused_passes": 1,
           "traffic_reduction_x": float(legacy_passes)}

    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    X = jax.random.normal(kx, (n, d), jnp.float32)
    y = jax.random.randint(ky, (n,), 0, n_classes,
                           jnp.int32).astype(jnp.float32)
    distinct = jnp.arange(n_classes, dtype=jnp.float32)
    clip = jnp.zeros(d, bool)
    w = jnp.ones(n, jnp.float32)

    def rep_x(r):
        # a fresh rep-varying matrix defeats executable result caching on
        # the tunnel; built OUTSIDE the timed window and dropped after
        # each rep so only TWO [n, d] copies are ever resident (4 at the
        # 10M TPU shape would hold ~10GB of a 16GB chip)
        xv = X + float(r)
        jax.block_until_ready(xv)
        return xv

    def fused_one(xv):
        st, _ = SE.fused_stats(xv, y, w, distinct=distinct, clip=clip,
                               corr_matrix=True)
        return st

    jax.block_until_ready(fused_one(X))  # warm/compile
    times = []
    for r in range(1, 4):
        xv = rep_x(r)
        t0 = time.perf_counter()
        jax.block_until_ready(fused_one(xv))
        times.append(time.perf_counter() - t0)
        del xv
    wall = min(times)
    roof = hbm_roof_gbps(jax.devices()[0].device_kind) if on_tpu else None
    rf = roofline_fields(wall, bytes_pass, roof)  # shared arithmetic:
    # this line must report the same numbers a collector stats_pass span
    # of the identical pass would
    out.update(fused_wall_s=round(wall, 4),
               achieved_gbps=rf["achieved_gbps"])
    if roof:
        out.update(hbm_roof_gbps=rf["roof_gbps"],
                   pct_of_hbm_roof=rf["pct_of_roof"])
    # the StatsPass telemetry shape (utils/metrics.StatsPass), verbatim,
    # so BENCH JSON consumers see the same struct a traced run records
    # next to kernel_roofline
    out["stats_pass"] = {
        "driver": "fused", "rows": n, "cols": d,
        "tiles": -(-n // SE.stats_row_block(d, n)),
        "bytes_hbm": int(bytes_pass), "wall_seconds": round(wall, 6),
        "passes": 1}

    # measured legacy route at the same shape: the separate reductions +
    # one contingency matmul per categorical group (what the pre-engine
    # SanityChecker dispatched)
    def legacy_one(xv):
        outs = [S.col_stats(xv), S.pearson_with_label(xv, y),
                S.pearson_matrix(xv), S.col_stats(y[:, None])]
        yoh = (y[:, None] == distinct[None, :]).astype(jnp.float32)
        for g in range(n_groups):
            cols = xv[:, 2 * g:2 * g + 2]
            outs.append(S.contingency_table(cols, yoh))
        return outs

    jax.block_until_ready(legacy_one(X))
    times = []
    for r in range(1, 4):
        xv = rep_x(r)
        t0 = time.perf_counter()
        jax.block_until_ready(legacy_one(xv))
        times.append(time.perf_counter() - t0)
        del xv
    out.update(legacy_wall_s=round(min(times), 4),
               speedup_x=round(min(times) / max(wall, 1e-9), 2))
    return out


# -- streaming data plane scenario (--streaming) ----------------------------

def streaming_bench(n_rows=None):
    """Scenario config for the tileplane streaming data plane
    (docs/performance.md "Streaming data plane"): an Avro file on disk is
    the ONLY copy of X; the bench streams it through every consumer —
    stats fit, GLM round fit, quantile binning + binned-matrix emission,
    and bulk scoring through a fitted workflow — reporting rows/s per
    phase plus the measured copy/compute overlap ratio, so the bench
    trajectory tracks this path like the flagship sweep. One JSON line;
    on CPU the numbers are liveness, not perf claims."""
    import shutil
    import tempfile

    import jax
    from transmogrifai_tpu.ops import glm_sweep as GS
    from transmogrifai_tpu.ops import stats_engine as SE
    from transmogrifai_tpu.ops import trees as TR
    from transmogrifai_tpu.parallel import tileplane as TP
    from transmogrifai_tpu.readers.avro import read_avro_file, \
        write_avro_file
    from transmogrifai_tpu.utils.metrics import collector

    backend = jax.default_backend()
    n = int(n_rows) if n_rows else (2_000_000 if backend == "tpu"
                                    else 50_000)
    d, F = 16, 3
    out = {"metric": "streaming_plane", "backend": backend,
           "n_rows": n, "n_cols": d, "tile_mb": TP.tile_budget_bytes() >> 20}

    tmp = tempfile.mkdtemp(prefix="bench_stream_")
    try:
        rng = np.random.default_rng(0)
        beta = rng.normal(size=d)
        schema = {"type": "record", "name": "Row", "fields": (
            [{"name": f"x{j}", "type": "float"} for j in range(d)]
            + [{"name": "y", "type": "float"},
               {"name": "id", "type": "long"}])}
        t0 = time.perf_counter()
        # written in SLABS of separate container files so the writer
        # holds at most one slab of records — the Avro directory really
        # is the only full copy of X
        slab = 250_000
        paths = []
        i = 0
        while i < n:
            rows = min(slab, n - i)
            recs = []
            for r_i in range(i, i + rows):
                x = rng.normal(size=d).astype(np.float32)
                recs.append({**{f"x{j}": float(x[j]) for j in range(d)},
                             "y": float(x @ beta > 0), "id": r_i})
            p = os.path.join(tmp, f"rows_{len(paths):04d}.avro")
            write_avro_file(p, schema, recs)
            paths.append(p)
            del recs
            i += rows
        out["write_s"] = round(time.perf_counter() - t0, 2)
        out["slabs"] = len(paths)

        def read_all():
            for p in paths:
                yield from read_avro_file(p)

        def stats_row(r):
            return ([r[f"x{j}"] for j in range(d)], r["y"], 1.0)

        def glm_row(r):
            m = [1.0] * F
            m[r["id"] % F] = 0.0
            return ([r[f"x{j}"] for j in range(d)], r["y"], 1.0, m)

        def src(fn):
            return TP.reader_row_source(read_all, fn,
                                        batch_records=8192, n_rows=n)

        # timed phases run UNTRACED: tracing inserts per-tile
        # block_until_ready fences the production path does not pay
        # (docs/observability.md "Tile spans"), so traced rows/s would
        # systematically understate the async pipeline
        t0 = time.perf_counter()
        SE.run_stats(src(stats_row), corr_matrix=True, label="bench")
        wall = time.perf_counter() - t0
        ps = SE._last_stream_stats
        out["stats_fit"] = {
            "wall_s": round(wall, 3),
            "rows_per_s": round(n / max(wall, 1e-9))}
        if ps is not None:  # None on the TMOG_TILEPLANE=0 legacy loop
            out["stats_fit"].update(tiles=ps.tiles,
                                    peak_host_rows=ps.peak_host_rows)

        t0 = time.perf_counter()
        _, _, info = GS.sweep_glm_streamed_rounds(
            src(glm_row), None, None, None,
            np.asarray([0.01, 0.1], np.float32),
            np.zeros(2, np.float32), loss="logistic", max_iter=15,
            tol=1e-5, warm_start=True)
        # the round driver returns HOST numpy coefficients — every
        # streamed pass already fenced on its delta fetch
        wall = time.perf_counter() - t0  # tmoglint: disable=TPU005
        out["glm_fit"] = {
            "wall_s": round(wall, 3),
            "data_passes": info["data_passes"],
            "rows_per_s_effective": round(
                n * max(info["data_passes"], 1) / max(wall, 1e-9)),
            "rounds": info["glm_rounds"]}

        t0 = time.perf_counter()
        edges = TR.stream_quantile_edges(src(stats_row), 32,
                                         hist_bins=512)
        # stats source yields (x, y, w); binning reads x only
        xonly = TP.IterSource(
            lambda: ((c[0],) for c in src(stats_row).chunks()),
            n_rows=n)
        binned = TR.stream_bin_matrix(xonly, edges)
        wall = time.perf_counter() - t0
        out["tree_binning"] = {
            "wall_s": round(wall, 3),
            "rows_per_s": round(n / max(wall, 1e-9)),
            "binned_mb": round(binned.nbytes / (1 << 20), 1)}
        del binned

        # separate TRACED probe pass just for the overlap ratio (its
        # wall is not the headline number)
        collector.enable("bench_streaming")
        try:
            SE.run_stats(src(stats_row), corr_matrix=True,
                         label="overlap_probe")
            ps = SE._last_stream_stats
            if ps is not None and ps.wall_seconds:
                out["overlap_probe"] = {
                    "overlap_ratio": round(
                        (ps.copy_seconds + ps.compute_seconds)
                        / max(ps.wall_seconds, 1e-9), 3),
                    "copy_s": round(ps.copy_seconds, 3),
                    "compute_s": round(ps.compute_seconds, 3),
                    "wall_s": round(ps.wall_seconds, 3)}
        finally:
            collector.finish()
            collector.disable()

        out["score"] = _streaming_score_phase(
            os.path.join(tmp, "rows_*.avro"), paths[0], d, n)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _streaming_score_phase(avro_pattern, train_path, d, n):
    """Train a tiny transmogrified workflow, then bulk-score the Avro
    stream through the tileplane scoring path (fixed record tiles,
    producer-thread Dataset assembly)."""
    import contextlib
    import io as _io

    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers import AvroStreamingReader, score_stream
    from transmogrifai_tpu.readers.avro import read_avro_file
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.workflow import Workflow

    train_rows = []
    for r in read_avro_file(train_path):
        train_rows.append(r)
        if len(train_rows) >= 5000:
            break
    preds = [FeatureBuilder.Real(f"x{j}").extract(
        lambda r, j=j: r.get(f"x{j}")).as_predictor() for j in range(d)]
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    vec = transmogrify(preds)
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, vec).get_output()
    with contextlib.redirect_stdout(_io.StringIO()):
        model = Workflow().set_reader(ListReader(train_rows)) \
            .set_result_features(pred).train()
    reader = AvroStreamingReader(avro_pattern)
    t0 = time.perf_counter()
    scored = sum(len(b) for b in score_stream(model, reader,
                                              tile_rows=4096))
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3), "rows_scored": int(scored),
            "rows_per_s": round(scored / max(wall, 1e-9))}


# -- sharded ingest A/B (--ingest-ab) ---------------------------------------

def ingest_ab_bench(n_rows=None):
    """Serial-vs-parallel ingest A/B over a multi-shard CSV input
    (docs/performance.md "Parallel sharded ingest"): three arms feed
    the SAME streamed stats fit — the legacy per-record reader source,
    the columnar sharded source at workers=1, and at workers=2 — and
    the bench reports pure parse rows/s (source drained with no device
    work), end-to-end fit wall + rows/s, a traced-probe device idle
    share (1 - compute/wall on the tileplane consumer), and a
    bit-identical check on the resulting moments. One JSON line; on CPU
    the numbers are liveness + speedup shape, not absolute perf."""
    import shutil
    import tempfile

    import jax
    from transmogrifai_tpu.ops import stats_engine as SE
    from transmogrifai_tpu.parallel import ingest as ING
    from transmogrifai_tpu.parallel import tileplane as TP
    from transmogrifai_tpu.readers.readers import CSVReader

    backend = jax.default_backend()
    n = int(n_rows) if n_rows else (2_000_000 if backend == "tpu"
                                    else 120_000)
    d, shards = 8, 8
    out = {"metric": "ingest_ab", "backend": backend, "n_rows": n,
           "n_cols": d, "shards": shards}

    tmp = tempfile.mkdtemp(prefix="bench_ingest_")
    try:
        rng = np.random.default_rng(0)
        per = -(-n // shards)
        t0 = time.perf_counter()
        paths = []
        for s in range(shards):
            rows = min(per, n - s * per)
            p = os.path.join(tmp, f"part-{s:03d}.csv")
            with open(p, "w") as fh:
                fh.write(",".join(f"x{j}" for j in range(d))
                         + ",y\n")
                block = rng.normal(size=(rows, d + 1))
                for r in block:
                    fh.write(",".join(f"{v:.6f}" for v in r) + "\n")
            paths.append(p)
        out["write_s"] = round(time.perf_counter() - t0, 2)

        def stats_cols(c):
            return (np.stack([c[f"x{j}"] for j in range(d)], 1),
                    c["y"], np.ones_like(c["y"]))

        def stats_row(r):
            return ([r[f"x{j}"] for j in range(d)], r["y"], 1.0)

        def legacy_source():
            def read_all():
                for p in paths:
                    yield from CSVReader(p).read()
            return TP.reader_row_source(read_all, stats_row,
                                        batch_records=8192, n_rows=n)

        def columnar_source(workers):
            return ING.sharded_reader_source(
                paths, stats_cols, batch_records=8192, n_rows=n,
                workers=workers, label=f"ab_w{workers}")

        arms = [("legacy_per_record", legacy_source),
                ("columnar_w1", lambda: columnar_source(1)),
                ("columnar_w2", lambda: columnar_source(2))]
        # warmup: compile the stats step once (same tile shape for all
        # arms) so no arm's fit wall carries the cold compile
        SE.run_stats(columnar_source(1), label="ab_warmup")
        means = {}
        for name, mk in arms:
            # pure parse: drain the chunk stream, no device in the loop
            t0 = time.perf_counter()
            rows = sum(int(c[0].shape[0]) for c in mk().chunks())
            parse_wall = time.perf_counter() - t0
            assert rows == n
            # end-to-end: the streamed stats fit (untraced — tracing
            # fences each tile and would understate the async pipeline)
            t0 = time.perf_counter()
            res = SE.run_stats(mk(), label=f"ab_{name}")
            fit_wall = time.perf_counter() - t0
            means[name] = (np.asarray(res.mean), np.asarray(res.m2))
            ps = SE._last_stream_stats
            arm = {"parse_wall_s": round(parse_wall, 3),
                   "parse_rows_per_s": round(n / max(parse_wall, 1e-9)),
                   "fit_wall_s": round(fit_wall, 3),
                   "fit_rows_per_s": round(n / max(fit_wall, 1e-9))}
            if ps is not None:
                arm["tiles"] = ps.tiles
            # separate TRACED probe for the idle share (compute-side
            # timings only accumulate under tracing): the fraction of
            # the pass wall the consumer spent NOT computing —
            # feed-starved headroom
            from transmogrifai_tpu.utils.metrics import collector
            collector.enable(f"bench_ingest_{name}")
            try:
                SE.run_stats(mk(), label=f"ab_probe_{name}")
                ps = SE._last_stream_stats
                if ps is not None and ps.wall_seconds:
                    arm["device_idle_share"] = round(
                        1.0 - ps.compute_seconds
                        / max(ps.wall_seconds, 1e-9), 3)
            finally:
                collector.finish()
                collector.disable()
            out[name] = arm

        ref_mean, ref_m2 = means["legacy_per_record"]
        out["bit_identical"] = bool(all(
            np.array_equal(m, ref_mean) and np.array_equal(q, ref_m2)
            for m, q in means.values()))
        legacy, w2 = out["legacy_per_record"], out["columnar_w2"]
        out["parse_speedup_w2_vs_legacy"] = round(
            w2["parse_rows_per_s"] / max(legacy["parse_rows_per_s"], 1),
            2)
        out["parse_speedup_w2_vs_w1"] = round(
            w2["parse_rows_per_s"]
            / max(out["columnar_w1"]["parse_rows_per_s"], 1), 2)
        out["fit_speedup_w2_vs_legacy"] = round(
            legacy["fit_wall_s"] / max(w2["fit_wall_s"], 1e-9), 2)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- multi-host pod A/B (--multihost) ---------------------------------------

# Child payload for the pod arms: runs inside launch_local_pod children,
# one per process. Each process opens ONLY its stripe of the shared CSV
# shard listing (multihost.stripe_paths via the ingest auto-stripe),
# drains it once for a pure-parse rate, then runs the streamed stats fit
# and a GLM gram sweep THROUGH the pod mesh — every psum a cross-process
# gloo collective when n_procs > 1. Rank 0 also reports a psum inventory
# (trace-time `psum` counts per sharded step program, via make_jaxpr —
# no execution) and a recompile probe (jax_log_compiles over a second
# identical stream pass; any count > 0 is a shape leak).
_MULTIHOST_CHILD = r"""
import glob, logging, os, re, time
import numpy as np
from transmogrifai_tpu.parallel import multihost as MH
MH.initialize()
import jax
pc = jax.process_count()
pid = jax.process_index()
mesh = MH.global_mesh(n_model=2)
d = int(os.environ["BENCH_MH_D"])
paths = sorted(glob.glob(os.path.join(os.environ["BENCH_MH_DIR"],
                                      "part-*.csv")))
from transmogrifai_tpu.ops import glm_sweep as GS
from transmogrifai_tpu.ops import stats_engine as SE
from transmogrifai_tpu.ops import trees as TR
from transmogrifai_tpu.parallel import ingest as ING

def stats_cols(c):
    return (np.stack([c["x%d" % j] for j in range(d)], 1), c["y"],
            np.ones_like(c["y"]))

mine = [str(p) for p in MH.stripe_paths(paths)]

def mk(n_rows=None, tag="parse"):
    # stripe=False: `mine` is already this process's stripe
    return ING.sharded_reader_source(mine, stats_cols, batch_records=8192,
                                     n_rows=n_rows, workers=1,
                                     stripe=False, label="mh_" + tag)

# pure parse: drain the local stripe, no device work in the loop
t0 = time.perf_counter()
chunks = list(mk().chunks())
n_local = sum(int(c[0].shape[0]) for c in chunks)
parse_wall = time.perf_counter() - t0

# streamed stats fit through the pod mesh: warm (compile) then timed
SE.stream_stats(mk(n_local, "warm"), mesh=mesh, corr_matrix=True)
t0 = time.perf_counter()
st, shift = SE.stream_stats(mk(n_local, "fit"), mesh=mesh,
                            corr_matrix=True)
stream_wall = time.perf_counter() - t0
ps = SE._last_stream_stats
tiles = ps.tiles if ps is not None else 0

# GLM gram sweep over the resident local rows, same mesh
Xl = np.concatenate([c[0] for c in chunks])
yl = (Xl[:, 0] > 0).astype(np.float32)
wl = np.ones(n_local, np.float32)
masks = np.zeros((2, n_local), np.float32)
masks[0, ::2] = 1.0
masks[1, 1::2] = 1.0
regs = np.asarray([1.0, 0.1, 0.01, 0.001], np.float32)
alphas = np.zeros(4, np.float32)
# block on the warm result: the gram program's gloo collectives must
# drain before the timed call's row_layout allgather, or two programs'
# collectives interleave on the pod's gloo context (size-mismatch abort)
jax.block_until_ready(GS.sweep_glm_squared_gram_sharded(
    mesh, Xl, yl, wl, masks, regs, alphas, max_iter=8))
t0 = time.perf_counter()
B, b0, iters = GS.sweep_glm_squared_gram_sharded(
    mesh, Xl, yl, wl, masks, regs, alphas, max_iter=8)
jax.block_until_ready(B)
glm_wall = time.perf_counter() - t0

# recompile probe: a second identical stream pass must hit the jit cache
class _Count(logging.Handler):
    def __init__(self):
        logging.Handler.__init__(self)
        self.n = 0
    def emit(self, r):
        if "ompil" in r.getMessage():
            self.n += 1

h = _Count()
jax.config.update("jax_log_compiles", True)
lg = logging.getLogger("jax")
lg.addHandler(h)
try:
    SE.stream_stats(mk(n_local, "re"), mesh=mesh, corr_matrix=True)
finally:
    jax.config.update("jax_log_compiles", False)
    lg.removeHandler(h)

out = {"pid": pid, "pc": pc, "n_local": n_local,
       "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
       "parse_wall_s": round(parse_wall, 3),
       "stream_wall_s": round(stream_wall, 3),
       "glm_wall_s": round(glm_wall, 3), "tiles": tiles,
       "recompiles_second_pass": h.n,
       "stats_mean0": float(np.asarray(st.mean)[0])}

if pid == 0:
    # psum inventory: trace-time collective count per sharded step
    def psums(fn, *args):
        return len(re.findall(r"\bpsum\b",
                              str(jax.make_jaxpr(fn)(*args))))
    nb = mesh.devices.shape[0]
    ns = 8 * nb
    Xs = np.zeros((ns, d), np.float32)
    ys = np.zeros(ns, np.float32)
    ws = np.ones(ns, np.float32)
    ms = np.ones((2, ns), np.float32)
    r2 = np.asarray([0.1, 0.01], np.float32)
    a2 = np.zeros(2, np.float32)
    inv = {"stats_fused_step": psums(
        SE._sharded_stats_fn(mesh, 0, True, False, False, False, False),
        Xs, ys, ws)}
    inv["glm_gram_sweep"] = psums(
        GS._sharded_gram_fn(mesh, True, True),
        Xs, ys, ws, ms, r2, a2, 8, 1e-6)
    static_kw = (("n_rounds", 2), ("depth", 2), ("n_bins", 8),
                 ("min_instances", 1.0), ("min_info_gain", 0.0),
                 ("subsample", 1.0), ("feature_frac", 1.0),
                 ("loss", "logistic"), ("interpret", False),
                 ("alpha", 0.0), ("max_delta_step", 0.0),
                 ("colsample_bylevel", 1.0), ("base_score", None))
    lane = np.full(2, 0.1, np.float32)
    inv["gbt_fit"] = psums(
        TR._sharded_gbt_fn(mesh, static_kw),
        np.zeros((ns, d), np.int32), ys, ms, jax.random.PRNGKey(0),
        lane, lane, lane, lane)
    out["psum_inventory"] = inv
    out["stream_psums_per_pass"] = tiles * inv["stats_fused_step"]

import json
print("RESULT|" + json.dumps(out), flush=True)
MH.finalize()
"""


def multihost_bench(n_rows=None):
    """Multi-host pod scaling A/B (docs/performance.md "Multi-host pod
    scaling"): the SAME 2x2 (data x lane) global mesh run as one
    process owning all 4 devices vs TWO processes owning 2 each
    (launch_local_pod, real jax.distributed children on localhost,
    cross-process psums over gloo). Each arm stripes the shared CSV
    shard listing per process, reports pure-parse rows/s, streamed
    stats + GLM gram fit walls, a per-step psum inventory, a recompile
    probe (second identical pass, expect 0), and a stats checksum that
    must agree across arms. On this box every process shares ONE core,
    so the parse "speedup" is a liveness + correctness measurement, not
    a perf claim — see liveness_note in the output."""
    import shutil
    import tempfile

    from transmogrifai_tpu.parallel.launch import launch_local_pod

    n = int(n_rows) if n_rows else 60_000
    d, shards = 8, 4
    out = {"metric": "multihost_ab", "n_rows": n, "n_cols": d,
           "shards": shards}

    tmp = tempfile.mkdtemp(prefix="bench_mh_")
    try:
        rng = np.random.default_rng(0)
        per = -(-n // shards)
        for s in range(shards):
            rows = min(per, n - s * per)
            with open(os.path.join(tmp, f"part-{s:03d}.csv"), "w") as fh:
                fh.write(",".join(f"x{j}" for j in range(d)) + ",y\n")
                for r in rng.normal(size=(rows, d + 1)):
                    fh.write(",".join(f"{v:.6f}" for v in r) + "\n")

        env = {"BENCH_MH_DIR": tmp, "BENCH_MH_D": str(d)}
        trace_dir = os.path.join(tmp, "podtrace")
        arms = {}
        for name, n_procs, dev in (("one_proc", 1, 4), ("two_proc", 2, 2)):
            # flight-record the real pod arm only: the recorder's value
            # is cross-process skew/collective-wait, meaningless at pc=1
            pod = launch_local_pod(_MULTIHOST_CHILD, n_procs=n_procs,
                                   devices_per_proc=dev, timeout=420.0,
                                   extra_env=env,
                                   trace_dir=(trace_dir if n_procs > 1
                                              else None))
            if not pod.ok:
                arms[name] = {"ok": False, "error": pod.error,
                              "stderr_tail": [c.stderr_tail[-400:]
                                              for c in pod.children]}
                continue
            res = [pod.result(i) for i in range(n_procs)]
            arm = {"ok": True, "n_procs": n_procs,
                   "devices_per_proc": dev, "mesh": res[0]["mesh"],
                   "rows_parsed": sum(r["n_local"] for r in res),
                   # the pod parses shard stripes concurrently: the pod
                   # rate is total rows over the SLOWEST stripe's wall
                   "parse_wall_s": max(r["parse_wall_s"] for r in res),
                   "stream_fit_wall_s": max(r["stream_wall_s"]
                                            for r in res),
                   "glm_fit_wall_s": max(r["glm_wall_s"] for r in res),
                   "tiles": res[0]["tiles"],
                   "recompiles_second_pass": sum(
                       r["recompiles_second_pass"] for r in res),
                   "stats_mean0": res[0]["stats_mean0"],
                   "pod_wall_s": round(pod.wall_s, 2)}
            arm["parse_rows_per_s"] = round(
                arm["rows_parsed"] / max(arm["parse_wall_s"], 1e-9))
            arm["psum_inventory"] = res[0].get("psum_inventory")
            arm["stream_psums_per_pass"] = res[0].get(
                "stream_psums_per_pass")
            arms[name] = arm
        out.update(arms)

        one, two = arms.get("one_proc"), arms.get("two_proc")
        if one and two and one.get("ok") and two.get("ok"):
            out["parse_speedup_2proc"] = round(
                two["parse_rows_per_s"] / max(one["parse_rows_per_s"], 1),
                2)
            out["stats_mean0_delta"] = abs(two["stats_mean0"]
                                           - one["stats_mean0"])
            out["liveness_note"] = (
                "both pod arms share one physical CPU core, so 2 "
                "processes cannot parse faster than 1 here — this A/B "
                "is a liveness and cross-arm-agreement measurement "
                "(real cross-process gloo psums, 0 recompiles, "
                "identical stats); per-host parse scaling needs "
                "per-host cores")

        # pod flight recorder on the real pod arm: merge the per-rank
        # artifact dirs into skew / collective-wait / MFU columns and
        # harvest the measured spans into the cpu-pc2 planner corpus
        # (docs/observability.md "Pod tracing"). This child runs
        # one-shot sharded entry points, no engine rounds, so the merge
        # aligns on one synthetic round — collective_share and the MFU
        # sinks are still exact (measured durations, analytic costs).
        if two and two.get("ok"):
            from transmogrifai_tpu.parallel import podtrace as PT
            rep = PT.merge_pod(trace_dir)
            out["pod_trace"] = {
                "rounds": len(rep["rounds"]),
                "synthetic_rounds": rep["synthetic_rounds"],
                "coverage_min_seen": rep["coverage_min_seen"],
                "collective_share": {
                    r["rank"]: r["collective_share"]
                    for r in rep["ranks"]},
                "collective_wait_s": {
                    r["rank"]: r["collective_s"]
                    for r in rep["ranks"]},
                "skew": rep["skew"],
                "mfu_top_sinks": rep["mfu_table"][:3],
                "problems": rep["problems"],
                "corpus_rows_harvested": PT.harvest_pod(trace_dir),
            }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- serving scenario (--serving) -------------------------------------------

def serving_bench(n_rows=None):
    """Scenario config for the production serving engine (serve/,
    docs/serving.md): a fitted workflow served through the bucket
    ladder — sustained bulk throughput through the top bucket, plus
    single-record p50/p99 through the micro-batching queue, BOTH read
    from the engine's own streaming latency histograms (the bench does
    not re-time what the engine already measures). One JSON line; on CPU
    the numbers are liveness, not perf claims."""
    import threading

    import jax
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.serve import MicroBatcher, ServingEngine
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.utils import tracing
    from transmogrifai_tpu.utils.metrics import collector
    from transmogrifai_tpu.workflow import Workflow

    backend = jax.default_backend()
    n_bulk = int(n_rows) if n_rows else (1_000_000 if backend == "tpu"
                                         else 100_000)
    d = 16
    out = {"metric": "serving", "backend": backend, "n_bulk": n_bulk,
           "n_cols": d}

    rng = np.random.default_rng(0)
    beta = rng.normal(size=d)

    def rec(i):
        x = rng.normal(size=d)
        return {**{f"x{j}": float(x[j]) for j in range(d)},
                "y": float(x @ beta > 0)}

    train_rows = [rec(i) for i in range(5000)]
    preds = [FeatureBuilder.Real(f"x{j}").extract(
        lambda r, j=j: r.get(f"x{j}")).as_predictor() for j in range(d)]
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    # a derived jitted feature so the prewarm/compile accounting is real
    fsum = (preds[0] + preds[1]) + 1.0
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify(preds + [fsum])).get_output()
    with contextlib.redirect_stdout(io.StringIO()):
        model = Workflow().set_reader(ListReader(train_rows)) \
            .set_result_features(pred).train()

    collector.enable("bench_serving")
    try:
        engine = ServingEngine(model, max_batch=4096, strict_keys=False)
        t0 = time.perf_counter()
        warm = engine.prewarm()
        out["prewarm"] = {"wall_s": warm["wall_s"],
                          "buckets": warm["buckets"],
                          "compiles": warm["compiles"],
                          "cache_hits": warm["cache_hits"]}
        base_compiles = tracing.tracker.true_compiles

        # bulk sustained throughput through the bucket ladder (the
        # engine chunks into top-bucket batches internally)
        bulk = [{k: v for k, v in rec(i).items() if k != "y"}
                for i in range(n_bulk)]
        t0 = time.perf_counter()
        scored = engine.score_batch(bulk)
        # score_batch returns host dicts — already synced
        wall = time.perf_counter() - t0  # tmoglint: disable=TPU005
        assert len(scored) == n_bulk
        out["bulk"] = {"wall_s": round(wall, 3),
                       "rows_per_s": round(n_bulk / max(wall, 1e-9)),
                       "bucket": engine.max_batch}
        del scored

        # the COLUMNAR bulk lane (readers/streaming.score_stream over the
        # tileplane): producer-thread Dataset assembly overlapped with
        # device scoring — the sustained-throughput path for row floods,
        # vs the request-shaped per-record ladder above
        from transmogrifai_tpu.readers import ListStreamingReader
        from transmogrifai_tpu.readers.streaming import score_stream
        t0 = time.perf_counter()
        n2 = sum(len(b) for b in score_stream(
            model, ListStreamingReader(bulk, batch_size=8192),
            tile_rows=4096))
        wall = time.perf_counter() - t0  # tmoglint: disable=TPU005
        assert n2 == n_bulk
        out["bulk_stream"] = {"wall_s": round(wall, 3),
                              "rows_per_s": round(n_bulk / max(wall, 1e-9)),
                              "tile_rows": 4096}
        del bulk

        # single-record latency through the micro-batcher, engine's own
        # histograms as the source of truth
        batcher = MicroBatcher(engine, max_wait_ms=1.0, max_queue=4096)
        singles = [{k: v for k, v in rec(i).items() if k != "y"}
                   for i in range(400)]
        for r in singles[:200]:  # sequential: isolated-request latency
            batcher.submit(r)
        errs = []

        def fire(rs):
            for r in rs:
                try:
                    batcher.submit(r)
                except Exception as e:  # noqa: BLE001 - recorded below
                    errs.append(repr(e))

        ths = [threading.Thread(target=fire,
                                args=(singles[200 + 25 * k:
                                              200 + 25 * (k + 1)],))
               for k in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        batcher.shutdown(drain=True)
        m = engine.metrics()
        out["single_record"] = {
            "requests": m["requests"],
            "p50_ms": m["latency"]["total"]["p50_ms"],
            "p99_ms": m["latency"]["total"]["p99_ms"],
            "queue_wait_p95_ms": m["latency"]["queue_wait"]["p95_ms"],
            "device_score_p50_ms": m["latency"]["device_score"]["p50_ms"],
        }
        out["post_warmup_compiles"] = \
            tracing.tracker.true_compiles - base_compiles
        out["shed"] = m["shed"]
        if errs:
            out["errors"] = errs[:5]

        # segment decomposition (docs/observability.md "Request
        # tracing"): where a request's wall actually goes, from the
        # engine's own per-segment histograms + pad accounting — the
        # numbers the fleet /requests endpoint merges across replicas
        lat = m["latency"]
        eng_hists = engine.hist
        total_s = eng_hists["total"].total_seconds
        out["segments"] = {
            "queue_wait_p50_ms": lat["queue_wait"]["p50_ms"],
            "queue_wait_p99_ms": lat["queue_wait"]["p99_ms"],
            "batch_assemble_p50_ms": lat["batch_assemble"]["p50_ms"],
            "device_score_p50_ms": lat["device_score"]["p50_ms"],
            "device_score_p99_ms": lat["device_score"]["p99_ms"],
            # padding share of all device rows (bulk + singles)
            "pad_fraction_mean": round(
                m["pad_rows"] / max(m["bucket_rows"], 1), 4),
            # device wall (batch walls counted once) over summed
            # request walls: the device share of the e2e latency mass
            "device_share": round(
                eng_hists["device_score"].total_seconds
                / max(total_s, 1e-9), 4),
        }

        # request-tracing on/off A/B (the tail-sampling layer's
        # request-path overhead pin): the IDENTICAL single-record mix
        # through fresh batchers on the SAME warm engine, submit walls
        # timed identically into bench-local histograms — tracing adds
        # one slotted record + a few perf_counter reads per request,
        # and this shows what that costs at p99
        from transmogrifai_tpu.serve import ReqTracer
        from transmogrifai_tpu.utils.metrics import LatencyHistogram

        def _drive_mix(trace_tracer):
            b = MicroBatcher(engine, max_wait_ms=1.0, max_queue=4096)
            h = LatencyHistogram("ab")
            errs_ab = []

            def one(r):
                t0 = time.perf_counter()
                rt = (trace_tracer.start(None)
                      if trace_tracer is not None else None)
                try:
                    b.submit(dict(r), trace=rt)
                    wall = time.perf_counter() - t0
                    if trace_tracer is not None:
                        trace_tracer.finish(rt, wall, status=200)
                    h.record(wall)
                except Exception as e:  # noqa: BLE001 - recorded
                    errs_ab.append(repr(e))

            for r in singles[:200]:
                one(r)
            ths = [threading.Thread(
                target=lambda k=k: [one(r) for r in
                                    singles[200 + 25 * k:
                                            200 + 25 * (k + 1)]])
                for k in range(8)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(120)
            b.shutdown(drain=True)
            return h, errs_ab

        h_off, e1 = _drive_mix(None)
        ab_tracer = ReqTracer("bench", sample_rate=0.05)
        h_on, e2 = _drive_mix(ab_tracer)
        j_off, j_on = h_off.to_json(), h_on.to_json()
        out["reqtrace_ab"] = {
            "p50_ms_off": j_off["p50_ms"], "p50_ms_on": j_on["p50_ms"],
            "p99_ms_off": j_off["p99_ms"], "p99_ms_on": j_on["p99_ms"],
            "p50_delta_ms": round(j_on["p50_ms"] - j_off["p50_ms"], 4),
            "p99_delta_ms": round(j_on["p99_ms"] - j_off["p99_ms"], 4),
            "traces": ab_tracer.n_traces,
            "kept": ab_tracer.n_kept,
        }
        if e1 or e2:
            out.setdefault("errors", []).extend((e1 + e2)[:5])

        # monitoring on/off A/B (docs/monitoring.md): the same single-
        # record + bulk traffic through a SECOND engine with the drift
        # monitor attached — p50/p99 delta and bulk rows/s overhead of
        # the per-bucket sketch program, sourced from the engines' own
        # histograms, so the drift tax rides the bench trajectory
        from transmogrifai_tpu.monitor import ServeMonitor, build_profile
        profile = build_profile(model)
        mon = ServeMonitor(profile, window_rows=4096, window_seconds=1e9)
        eng_on = ServingEngine(model, max_batch=4096, strict_keys=False,
                               monitor=mon)
        eng_on.prewarm()
        base_on = tracing.tracker.true_compiles
        bulk = [{k: v for k, v in rec(i).items() if k != "y"}
                for i in range(n_bulk)]
        t0 = time.perf_counter()
        assert len(eng_on.score_batch(bulk)) == n_bulk
        # score_batch returns host dicts — already synced
        wall_on = time.perf_counter() - t0  # tmoglint: disable=TPU005
        del bulk
        # IDENTICAL single-record mix to the baseline phase (200
        # sequential + 8x25 concurrent): the p50/p99 delta must isolate
        # the sketch overhead, not a different queue-wait profile
        b_on = MicroBatcher(eng_on, max_wait_ms=1.0, max_queue=4096)
        for r in singles[:200]:
            b_on.submit(dict(r))
        errs_on = []

        def fire_on(rs):
            for r in rs:
                try:
                    b_on.submit(dict(r))
                except Exception as e:  # noqa: BLE001 - recorded below
                    errs_on.append(repr(e))

        ths = [threading.Thread(target=fire_on,
                                args=(singles[200 + 25 * k:
                                              200 + 25 * (k + 1)],))
               for k in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        b_on.shutdown(drain=True)
        eng_on.finish_monitor()
        if errs_on:
            out.setdefault("errors", []).extend(errs_on[:5])
        m_on = eng_on.metrics()
        rows_s_off = out["bulk"]["rows_per_s"]
        rows_s_on = round(n_bulk / max(wall_on, 1e-9))
        out["monitor_ab"] = {
            "windows": m_on["monitor"]["windows"],
            "alerts": m_on["monitor"]["alerts_total"],
            "post_warmup_compiles_on": (tracing.tracker.true_compiles
                                        - base_on),
            "single_p50_ms_off": out["single_record"]["p50_ms"],
            "single_p50_ms_on": m_on["latency"]["total"]["p50_ms"],
            "single_p99_ms_off": out["single_record"]["p99_ms"],
            "single_p99_ms_on": m_on["latency"]["total"]["p99_ms"],
            "p50_delta_ms": round(m_on["latency"]["total"]["p50_ms"]
                                  - out["single_record"]["p50_ms"], 4),
            "p99_delta_ms": round(m_on["latency"]["total"]["p99_ms"]
                                  - out["single_record"]["p99_ms"], 4),
            "bulk_rows_per_s_off": rows_s_off,
            "bulk_rows_per_s_on": rows_s_on,
            "bulk_overhead_pct": round(
                100.0 * (rows_s_off - rows_s_on) / max(rows_s_off, 1),
                2),
        }
    finally:
        collector.finish()
        collector.disable()
    return out


# -- fleet scenario (--fleet) ------------------------------------------------

def fleet_bench(n_requests=None):
    """Scenario config for the serving fleet (fleet/, docs/fleet.md):
    the same tiny model served DIRECT (in-process engine + batcher),
    then behind the front router with 1 and with 2 real replica
    subprocesses — per-config rows/s and p50/p99 from the router's own
    histogram, plus the router-overhead decomposition (fleet p50 minus
    the replica-reported engine p50: HTTP hop + routing). Replica
    children run on the CPU backend (the overhead being measured is
    host-side); one JSON line."""
    import shutil
    import tempfile
    import threading

    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.fleet import (HealthProber, Router, Supervisor)
    from transmogrifai_tpu.fleet.frontend import FleetFrontend
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.serve import MicroBatcher, ServingEngine
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.workflow import Workflow

    n_req = int(n_requests) if n_requests else 300
    d = 8
    rng = np.random.default_rng(0)
    beta = rng.normal(size=d)

    def rec(i):
        x = rng.normal(size=d)
        return {**{f"x{j}": float(x[j]) for j in range(d)},
                "y": float(x @ beta > 0)}

    train_rows = [rec(i) for i in range(2000)]
    preds = [FeatureBuilder.Real(f"x{j}").extract(
        lambda r, j=j: r.get(f"x{j}")).as_predictor() for j in range(d)]
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    fsum = (preds[0] + preds[1]) + 1.0
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify(preds + [fsum])).get_output()
    with contextlib.redirect_stdout(io.StringIO()):
        model = Workflow().set_reader(ListReader(train_rows)) \
            .set_result_features(pred).train()

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    out = {"metric": "fleet", "n_requests": n_req}
    try:
        mdir = os.path.join(tmp, "model")
        model.save(mdir)
        records = [{k: v for k, v in rec(i).items() if k != "y"}
                   for i in range(n_req)]

        # DIRECT baseline: in-process engine + micro-batcher
        engine = ServingEngine(mdir, max_batch=16, strict_keys=False)
        engine.prewarm()
        batcher = MicroBatcher(engine, max_wait_ms=1.0, max_queue=4096)
        t0 = time.perf_counter()
        for r in records:
            batcher.submit(r)
        # submit blocks per record: wall is the sequential total
        wall = time.perf_counter() - t0  # tmoglint: disable=TPU005
        batcher.shutdown(drain=True)
        md = engine.metrics()
        out["direct"] = {
            "rows_per_s": round(n_req / max(wall, 1e-9)),
            "p50_ms": md["latency"]["total"]["p50_ms"],
            "p99_ms": md["latency"]["total"]["p99_ms"]}

        env = {"JAX_PLATFORMS": "cpu",
               "TMOG_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
               "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))}
        for n_replicas in (1, 2):
            lock = threading.RLock()
            sup = Supervisor(
                mdir, replicas=n_replicas, lock=lock,
                metrics_root=os.path.join(tmp, f"fleet{n_replicas}"),
                serve_args=["--max-batch", "16", "--max-wait-ms", "1",
                            "--monitor", "off"],
                env=env, startup_timeout_s=300.0)
            router = Router(lock, request_timeout=60.0)
            prober = None
            try:
                router.set_champions(sup.start())
                prober = HealthProber(router, interval_s=0.25).start()
                fe = FleetFrontend(sup, router)
                errs = []

                def fire(rs):
                    for r in rs:
                        try:
                            fe.submit(r)
                        except Exception as e:  # noqa: BLE001
                            errs.append(repr(e))

                chunk = max(n_req // 4, 1)
                t0 = time.perf_counter()
                ths = [threading.Thread(
                    target=fire, args=(records[k * chunk:
                                               (k + 1) * chunk],))
                    for k in range(4)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join(600)
                # fe.submit returns parsed responses: all synced
                wall = time.perf_counter() - t0  # tmoglint: disable=TPU005
                served = router.n_requests
                fm = fe.metrics()
                rj = router.hist.to_json()
                engine_p50 = fm["latency"].get("total", {}).get("p50_ms")
                cfg = {
                    "rows_per_s": round(served / max(wall, 1e-9)),
                    "p50_ms": rj["p50_ms"], "p99_ms": rj["p99_ms"],
                    "engine_p50_ms": engine_p50,
                    "router_overhead_p50_ms": (
                        round(rj["p50_ms"] - engine_p50, 4)
                        if engine_p50 is not None else None),
                    "retries": router.n_retries, "shed": router.n_shed,
                    "post_warmup_compiles": fm["post_warmup_compiles"],
                }
                if errs:
                    cfg["errors"] = errs[:5]
                out[f"replicas_{n_replicas}"] = cfg
            finally:
                if prober is not None:
                    prober.stop()
                sup.stop(router=router)
        r1 = out.get("replicas_1", {}).get("rows_per_s") or 1
        r2 = out.get("replicas_2", {}).get("rows_per_s")
        if r2:
            out["scaling_2_over_1"] = round(r2 / r1, 3)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# -- plan-time autotuning A/B (--plan-ab) -----------------------------------

#: the flagship-shaped (scaled) config both plan-A/B arms run — seeds are
#: fixed inside device_data/glm_grids/gbt_grids, so the two arms execute
#: the IDENTICAL workload and differ only in TMOG_PLAN
PLAN_AB_CFG = dict(n_rows=100_000, n_cols=32, folds=5, glm_grid=12,
                   gbt_grid=4, gbt_rounds=5, gbt_depth=4, gbt_bins=32,
                   serve_singles=300, serve_max_batch=64)


def harvest_spans_to_corpus(src):
    """Append this process's TraceTree kernel spans to the plan corpus
    (docs/planning.md): every bench run makes the planner smarter.
    Best-effort by contract — corpus IO must never fail a bench."""
    try:
        import tempfile
        from transmogrifai_tpu.planner.corpus import (Corpus,
                                                      harvest_metrics_file)
        from transmogrifai_tpu.planner.plan import corpus_dir
        from transmogrifai_tpu.utils.metrics import collector
        if not collector.enabled:
            return 0
        import jax
        backend = jax.default_backend()
        fd, tmp = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            collector.save(tmp, close=False)
            recs = harvest_metrics_file(tmp, backend, src=src)
        finally:
            os.unlink(tmp)
        return Corpus(corpus_dir()).append(recs) if recs else 0
    except Exception:
        return 0


def plan_ab_arm(arm):
    """Child body (--plan-ab-arm hand|auto): the identical seeded
    workload under TMOG_PLAN=0 (hand plan) or TMOG_PLAN=1 (autotuned).

    Phases: the flagship-shaped GLM + tree sweeps through the framework
    validator (cold then warm — warm is the plan-quality signal, cold
    includes compiles), then a serving phase whose p50/p99 come from the
    ENGINE'S OWN latency histograms (the bench does not re-time what the
    engine measures). The resolved FitPlan/ServePlan ride along with full
    per-decision provenance, and the run's kernel spans are appended to
    the corpus before exiting. One PLANAB| JSON line out."""
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.automl.tuning.validators import CrossValidation
    from transmogrifai_tpu.evaluators.evaluators import Evaluators
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.models.trees import OpXGBoostClassifier
    from transmogrifai_tpu.planner import plan_enabled, plan_fit, \
        plan_serving
    from transmogrifai_tpu.utils.metrics import collector

    cfg = json.loads(os.environ.get("BENCH_PLAN_AB_CFG") or "null") \
        or dict(PLAN_AB_CFG)
    backend = jax.default_backend()
    out = {"arm": arm, "backend": backend,
           "plan_enabled": plan_enabled(), "cfg": cfg}
    collector.enable(f"plan_ab_{arm}")

    X, y, _ = device_data(cfg["n_rows"], cfg["n_cols"], cfg["folds"],
                          jnp.float32)
    ev = Evaluators.BinaryClassification.au_pr()
    val = CrossValidation(ev, num_folds=cfg["folds"], seed=42)
    lr = OpLogisticRegression(max_iter=15, standardization=False)
    ggrids = [dict(g) for g in glm_grids(cfg["glm_grid"])]
    tgrids = [dict(g) for g in gbt_grids(cfg)]

    t0 = time.perf_counter()
    best_glm = val.validate([(lr, [dict(g) for g in ggrids])], X, y)
    # tmoglint: disable=TPU005  validate() blocks via np.asarray
    glm_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    val.validate([(lr, [dict(g) for g in ggrids])], X, y)
    # tmoglint: disable=TPU005  validate() blocks via np.asarray
    glm_warm_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    best_tree = val.validate([(OpXGBoostClassifier(),
                               [dict(g) for g in tgrids])], X, y)
    # tmoglint: disable=TPU005  validate() blocks via np.asarray
    tree_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    val.validate([(OpXGBoostClassifier(),
                   [dict(g) for g in tgrids])], X, y)
    # tmoglint: disable=TPU005  validate() blocks via np.asarray
    tree_warm_s = time.perf_counter() - t0

    out["sweep"] = {
        "glm_cold_s": round(glm_cold_s, 3),
        "glm_warm_s": round(glm_warm_s, 3),
        "tree_cold_s": round(tree_cold_s, 3),
        "tree_warm_s": round(tree_warm_s, 3),
        "warm_total_s": round(glm_warm_s + tree_warm_s, 3),
        "cold_total_s": round(glm_cold_s + tree_cold_s, 3),
        "glm_route": best_glm.validated[0].route,
        "glm_au_pr": round(float(best_glm.best_metric), 4),
        "tree_au_pr": round(float(best_tree.best_metric), 4)}

    out["serving"] = _plan_ab_serving(cfg)

    # the resolved plans, with per-decision provenance — what actually
    # differed between the arms, straight from the choke point the call
    # sites consult
    fit_plan = plan_fit(cfg["n_rows"], cfg["n_cols"],
                        n_folds=cfg["folds"], n_grids=cfg["glm_grid"],
                        depth=cfg["gbt_depth"], n_bins=cfg["gbt_bins"])
    serve_plan = plan_serving(cfg["serve_max_batch"])
    out["plan"] = fit_plan.to_json()
    out["serve_buckets"] = list(serve_plan.buckets)
    out["corpus_harvested"] = harvest_spans_to_corpus(f"plan_ab_{arm}")
    collector.disable()
    print("PLANAB|" + json.dumps(out), flush=True)


def _plan_ab_serving(cfg):
    """Serving phase of one A/B arm: tiny fitted workflow served through
    the (planned or hand) bucket ladder; p50/p99 read from the engine's
    own histograms."""
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.automl import BinaryClassificationModelSelector
    from transmogrifai_tpu.automl.transmogrifier import transmogrify
    from transmogrifai_tpu.models.glm import OpLogisticRegression
    from transmogrifai_tpu.readers.readers import ListReader
    from transmogrifai_tpu.serve import MicroBatcher, ServingEngine
    from transmogrifai_tpu.stages.params import param_grid
    from transmogrifai_tpu.workflow import Workflow

    d = 8
    rng = np.random.default_rng(0)
    beta = rng.normal(size=d)

    def rec(i):
        x = rng.normal(size=d)
        return {**{f"x{j}": float(x[j]) for j in range(d)},
                "y": float(x @ beta > 0)}

    train_rows = [rec(i) for i in range(2000)]
    preds = [FeatureBuilder.Real(f"x{j}").extract(
        lambda r, j=j: r.get(f"x{j}")).as_predictor() for j in range(d)]
    fy = FeatureBuilder.RealNN("y").extract(
        lambda r: r.get("y")).as_response()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        models_and_parameters=[(OpLogisticRegression(),
                                param_grid(reg_param=[0.01]))],
    ).set_input(fy, transmogrify(preds)).get_output()
    with contextlib.redirect_stdout(io.StringIO()):
        model = Workflow().set_reader(ListReader(train_rows)) \
            .set_result_features(pred).train()

    engine = ServingEngine(model, max_batch=cfg["serve_max_batch"],
                           strict_keys=False)
    warm = engine.prewarm()
    batcher = MicroBatcher(engine, max_wait_ms=1.0, max_queue=4096)
    singles = [{k: v for k, v in rec(i).items() if k != "y"}
               for i in range(cfg["serve_singles"])]
    for r in singles:
        batcher.submit(r)
    batcher.shutdown(drain=True)
    m = engine.metrics()
    return {"buckets": warm["buckets"],
            "prewarm_s": warm["wall_s"],
            "requests": m["requests"],
            "p50_ms": m["latency"]["total"]["p50_ms"],
            "p99_ms": m["latency"]["total"]["p99_ms"],
            "device_score_p50_ms":
                m["latency"]["device_score"]["p50_ms"]}


def plan_ab_bench():
    """--plan-ab parent: hand plan (TMOG_PLAN=0) vs autotuned plan
    (TMOG_PLAN=1) over the identical seeded workload, each arm in its own
    child process so neither inherits the other's warm jit caches. A cold
    corpus is seeded first through `plan calibrate` (skippable with
    BENCH_PLAN_AB_CALIBRATE=0 — then a cold corpus makes the arms
    bit-identical by the no-op guarantee). The verdict `autotuned_ok`
    asserts the autotuned plan is no slower than the hand plan OUTSIDE
    the noise margin (BENCH_PLAN_AB_NOISE, default 15% — single-shot
    walls on a contended box swing), on both the warm sweep wall and the
    serving p50."""
    from transmogrifai_tpu.planner.corpus import Corpus
    from transmogrifai_tpu.planner.plan import corpus_dir

    backend, kind = probe_backend()
    if backend is None:
        backend = "cpu"
    env_base = dict(os.environ)
    if backend == "cpu":
        env_base["JAX_PLATFORMS"] = "cpu"
    path = corpus_dir()
    env_base["TMOG_PLAN_CORPUS_DIR"] = path
    corpus = Corpus(path)
    out = {"metric": "plan_ab", "backend": backend, "corpus_dir": path}

    n_before = len(corpus.load(backend))
    if n_before == 0 and \
            os.environ.get("BENCH_PLAN_AB_CALIBRATE", "1") != "0":
        log("cold corpus: seeding via `plan calibrate`")
        env = dict(env_base)
        env.pop("TMOG_PLAN", None)
        try:
            r = subprocess.run(
                [sys.executable, "-m", "transmogrifai_tpu", "plan",
                 "calibrate", "--budget-s", "150", "--scale", "0.5"],
                capture_output=True, text=True, timeout=600, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            try:
                out["calibration"] = json.loads(
                    r.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                out["calibration"] = {"rc": r.returncode,
                                      "stderr": (r.stderr or "")[-300:]}
        except subprocess.TimeoutExpired:
            # a hung calibrate must not kill the A/B: the cold corpus
            # makes both arms bit-identical (the no-op guarantee)
            out["calibration"] = {"error": "HANG killed at 600s"}
    out["corpus_records"] = len(corpus.load(backend))

    arms = {}
    for arm in ("hand", "auto"):
        env = dict(env_base)
        env["TMOG_PLAN"] = "0" if arm == "hand" else "1"
        log(f"plan-ab arm: {arm} (TMOG_PLAN={env['TMOG_PLAN']})")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--plan-ab-arm", arm],
                capture_output=True, text=True, timeout=1800, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            # fault-isolation contract: a hung arm records an error and
            # the parent still emits its one JSON line
            out.setdefault("errors", []).append(
                f"{arm} arm: HANG killed at 1800s")
            continue
        line = next((l for l in (r.stdout or "").splitlines()
                     if l.startswith("PLANAB|")), None)
        if line is None:
            out.setdefault("errors", []).append(
                f"{arm} arm rc={r.returncode}: "
                f"{(r.stderr or '').strip()[-300:]}")
            continue
        arms[arm] = json.loads(line[7:])
        log(f"plan-ab {arm}: sweep={arms[arm]['sweep']['warm_total_s']}s "
            f"serve_p50={arms[arm]['serving']['p50_ms']}ms")
    out["hand"], out["auto"] = arms.get("hand"), arms.get("auto")

    if "hand" in arms and "auto" in arms:
        noise = float(os.environ.get("BENCH_PLAN_AB_NOISE", "0.15"))
        h_sweep = arms["hand"]["sweep"]["warm_total_s"]
        a_sweep = arms["auto"]["sweep"]["warm_total_s"]
        h_p50 = arms["hand"]["serving"]["p50_ms"]
        a_p50 = arms["auto"]["serving"]["p50_ms"]
        # the serving verdict judges the DEVICE-SCORE histogram — the
        # number the planned ladder actually moves (padding waste per
        # bucket). End-to-end single p50 is reported alongside but is
        # dominated by the micro-batcher's max_wait timer jitter on a
        # contended box (±1ms run to run), which no plan controls.
        h_dev = arms["hand"]["serving"]["device_score_p50_ms"]
        a_dev = arms["auto"]["serving"]["device_score_p50_ms"]
        hv = {n: d["value"]
              for n, d in arms["hand"]["plan"]["decisions"].items()}
        av = {n: d["value"]
              for n, d in arms["auto"]["plan"]["decisions"].items()}
        out["deltas"] = {
            "noise_margin": noise,
            "sweep_warm_hand_s": h_sweep, "sweep_warm_auto_s": a_sweep,
            "sweep_auto_over_hand": round(a_sweep / max(h_sweep, 1e-9),
                                          3),
            "serve_p50_hand_ms": h_p50, "serve_p50_auto_ms": a_p50,
            "serve_device_p50_hand_ms": h_dev,
            "serve_device_p50_auto_ms": a_dev,
            "decisions_moved": sorted(
                n for n in hv if av.get(n) != hv[n]),
            "glm_au_pr_delta": round(
                arms["auto"]["sweep"]["glm_au_pr"]
                - arms["hand"]["sweep"]["glm_au_pr"], 4)}
        out["autotuned_ok"] = bool(
            a_sweep <= h_sweep * (1 + noise)
            and a_dev <= h_dev * (1 + noise) + 0.05)
    return out


# -- cpu-subprocess phases --------------------------------------------------
# Tiny example flows and the host-transform-dominated wide bench dispatch
# hundreds of small programs; over a remote TPU tunnel every dispatch pays
# an RPC, so they run in CPU-backend child processes (the number being
# measured — host transform throughput / end-to-end capability — is the
# same) with hard timeouts so no phase can starve the headline metric.

def run_subprocess_phase(args, timeout_s, compile_cache=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # keep the axon sitecustomize off the child's path (it dials the TPU
    # tunnel at interpreter start — round-1 hang)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    # cold numbers must stay cold across bench reruns: the user-level
    # persistent compile cache would warm them invisibly, so each phase
    # gets an explicit cache dir ("0" disables; a per-run temp dir makes
    # a controlled cold -> warm pair)
    env["TMOG_COMPILE_CACHE"] = compile_cache or "0"
    r = subprocess.run([sys.executable, os.path.abspath(__file__)] + args,
                       capture_output=True, text=True, timeout=timeout_s,
                       env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    if r.returncode != 0:
        raise RuntimeError(f"phase {args} rc={r.returncode}: "
                           f"{r.stderr.strip()[-300:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_example(mod_name):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "examples"))
    sys.argv = sys.argv[:1]  # examples parse argv (CSV path arg)
    import importlib
    t0 = time.perf_counter()
    with contextlib.redirect_stdout(io.StringIO()):
        mod = importlib.import_module(mod_name)
        mod.main()
    return time.perf_counter() - t0


#: reference checkout's canonical Titanic training file (headerless)
REF_TITANIC = ("/root/reference/helloworld/src/main/resources/"
               "TitanicDataset/TitanicPassengersTrainData.csv")
#: the reference's published holdout metrics for this flow
#: (/root/reference/README.md:84-96)
TITANIC_PUBLISHED = {"au_roc": 0.8822, "au_pr": 0.8225}


def titanic_quality():
    """Model-quality parity on the canonical real dataset: train the full
    OpTitanicSimple flow on the reference's own CSV and report holdout
    AuROC/AuPR against its published run — quality evidence that lands in
    the artifact on ANY backend, not just when the TPU sweep runs."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "examples"))
    import op_titanic_simple as t
    from transmogrifai_tpu.readers.readers import CSVReader

    with contextlib.redirect_stdout(io.StringIO()):
        wf, _ = t.build_workflow()
        model = wf.set_reader(
            CSVReader(REF_TITANIC, columns=t.PASSENGER_COLUMNS)).train()
    hold = model.selector_summary().holdout_evaluation
    out = {"holdout_au_roc": round(float(hold["au_roc"]), 4),
           "holdout_au_pr": round(float(hold["au_pr"]), 4)}
    for k, pub in TITANIC_PUBLISHED.items():
        out[f"published_{k}"] = pub
        out[f"delta_{k}"] = round(float(hold[k]) - pub, 4)
    return out


# -- main -------------------------------------------------------------------

def main():
    # subcommands executed in CPU child processes
    if len(sys.argv) > 2 and sys.argv[1] == "--wide":
        print(json.dumps(wide_transmogrify(int(sys.argv[2]))))
        return
    if len(sys.argv) > 2 and sys.argv[1] == "--example":
        print(json.dumps({"s": round(run_example(sys.argv[2]), 2)}))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--quality":
        print(json.dumps(titanic_quality()))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--hist-roofline":
        print(json.dumps(hist_roofline_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--stats-roofline":
        print(json.dumps(stats_roofline_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--streaming":
        print(json.dumps(streaming_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--ingest-ab":
        print(json.dumps(ingest_ab_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--multihost":
        res = multihost_bench(sys.argv[2] if len(sys.argv) > 2 else None)
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "MULTICHIP_r07.json")
        with open(path, "w") as fh:
            json.dump(res, fh, indent=2)
        print(json.dumps(res))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--serving":
        print(json.dumps(serving_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--fleet":
        print(json.dumps(fleet_bench(
            sys.argv[2] if len(sys.argv) > 2 else None)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--tree-sweep":
        cfg_json = os.environ.get("BENCH_TREE_CFG")
        tree_sweep_child(json.loads(cfg_json) if cfg_json
                         else dict(TPU_CFG))
        return
    if len(sys.argv) > 2 and sys.argv[1] == "--plan-ab-arm":
        plan_ab_arm(sys.argv[2])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--plan-ab":
        print(json.dumps(plan_ab_bench()), flush=True)
        return

    signal.signal(signal.SIGALRM, emit_and_exit)
    signal.alarm(max(int(BUDGET_S) - 30, 60))

    backend, kind = probe_backend()
    errors = []
    RESULT["errors"] = errors
    # optional hierarchical trace of the whole bench (docs/observability.md):
    # BENCH_TRACE_DIR=<dir> writes bench_trace.json (Perfetto), the span-tree
    # stage-metrics JSON and a streaming events.jsonl there; inspect with
    # `python -m transmogrifai_tpu trace-report <dir>`
    trace_dir = TRACE_DIR
    if trace_dir:
        from transmogrifai_tpu.utils.metrics import collector as _coll
        os.makedirs(trace_dir, exist_ok=True)
        _coll.enable("bench")
        _coll.attach_event_log(os.path.join(trace_dir, "events.jsonl"))
        _coll.event("run_start", run_type="bench")
    if backend is None or backend == "cpu":
        from transmogrifai_tpu.utils.platform import force_cpu
        force_cpu(1)
        if backend is None:
            errors.append("tpu backend unreachable; cpu fallback at "
                          "reduced size")
        backend, kind = "cpu", kind or "cpu"
        cfg = dict(CPU_CFG)
        sweep_dtype = None  # f32 — CPU matmuls have no bf16 units
    else:
        cfg = dict(TPU_CFG)
        import jax.numpy as jnp
        sweep_dtype = jnp.bfloat16
    RESULT.update(backend=backend, device_kind=kind, n_rows=cfg["n_rows"],
                  config=f"{cfg['glm_grid']}+{cfg['gbt_grid']} models x "
                         f"{cfg['folds']} folds")
    log(f"backend={backend} kind={kind} cfg={cfg}")
    persist_partial("backend_probe")

    # 1. headline sweep — data generated ON DEVICE (no tunnel transfer)
    import jax.numpy as jnp
    t0 = time.perf_counter()
    Xd, yd, _ = device_data(cfg["n_rows"], cfg["n_cols"],
                            cfg["folds"], sweep_dtype or jnp.float32)
    log(f"device data gen: {time.perf_counter() - t0:.2f}s")

    sweep = device_sweeps(Xd, yd, cfg, sweep_dtype, errors)
    device_s = max(sweep["glm_s"] + sweep["tree_s"], 1e-9)
    RESULT.update(metric=f"cv_sweep_{cfg['n_rows'] / 1e6:g}m_rows_"
                         f"{cfg['glm_grid'] + cfg['gbt_grid']}"
                         f"model_{cfg['folds']}fold_wall",
                  value=round(device_s, 3), sweep=sweep)
    if sweep.get("kernel_roofline"):
        RESULT["kernel_roofline"] = sweep["kernel_roofline"]
    persist_partial("device_sweeps")

    # 2. MFU — count only families whose device sweep actually ran, with
    # the FLOP model matched to the route that produced the timing and to
    # the sweep's own executed-pass telemetry
    glm_flops = (glm_flops_estimate(cfg, sweep.get("glm_route"),
                                    sweep.get("glm_telemetry"))
                 if sweep["glm_fits"] else 0.0)
    per_fit = (sweep.get("tree_fit_flops")
               or (tree_flops_cost_analysis(cfg, sweep_dtype)
                   if sweep["tree_fits"] else 0.0))
    tree_flops = per_fit * cfg["gbt_grid"] * cfg["folds"] \
        if sweep["tree_fits"] else 0.0
    peak = next((p for s, p in PEAK_BF16 if s in kind.lower()), None)
    mfu = {"glm_tflops_analytic": round(glm_flops / 1e12, 2),
           "tree_tflops_xla": round(tree_flops / 1e12, 2),
           "achieved_tflops_per_s": round(
               (glm_flops + tree_flops) / device_s / 1e12, 2)}
    glm_warm = sweep.get("glm_warm_s")
    if glm_warm:
        mfu["glm_achieved_tflops_warm"] = round(
            glm_flops / glm_warm / 1e12, 2)
    if peak and backend == "tpu":
        mfu["peak_bf16_tflops"] = peak / 1e12
        mfu["mfu"] = round((glm_flops + tree_flops) / device_s / peak, 4)
        if glm_warm:
            mfu["glm_mfu_warm"] = round(glm_flops / glm_warm / peak, 4)
    mfu["glm_flop_model"] = (sweep.get("glm_route") or "n/a") + (
        ":measured_passes"
        if (sweep.get("glm_telemetry") or {}).get("lane_passes")
        else (":assumed_15it" if sweep.get("glm_route") else ""))
    RESULT["mfu"] = mfu
    persist_partial("mfu")

    # 3. measured host baseline (independent same-distribution twin; fixed
    # iteration counts make the cost data-independent)
    log(f"host twin gen {cfg['n_rows']} x {cfg['n_cols']}")
    Xh, yh = make_data(cfg["n_rows"], cfg["n_cols"], seed=1)
    rng = np.random.default_rng(7)
    fold = rng.integers(0, cfg["folds"], size=cfg["n_rows"])
    masks_h = np.stack([(fold != k).astype(np.float32)
                        for k in range(cfg["folds"])])
    glm_fit_s, glm_total = (baseline_glm(Xh, yh, masks_h, cfg)
                            if sweep["glm_fits"] else (0.0, 0.0))
    gbt_round_s, gbt_total = (baseline_gbt(Xh, yh, masks_h, cfg)
                              if sweep["tree_fits"] else (0.0, 0.0))
    # compare like with like: only count baseline families whose device
    # sweep actually ran (a family zeroed by a device failure would
    # otherwise inflate the ratio)
    base_total = (glm_total if sweep["glm_fits"] else 0.0) \
        + (gbt_total if sweep["tree_fits"] else 0.0)
    RESULT["baseline"] = {
        "total_s": round(base_total, 1),
        "glm_fit_s_measured": round(glm_fit_s, 2),
        "gbt_round_s_measured": round(gbt_round_s, 2),
        "method": ("sequential host numpy/BLAS (multithreaded); per-fit / "
                   "per-round cost measured at the FULL row count, totals "
                   "are cost x config x fold counts (configs within a "
                   "family are cost-identical). Generous vs Spark-local: "
                   "no JVM/DataFrame overhead counted."),
    }
    RESULT["vs_baseline"] = round(base_total / device_s, 2)
    RESULT["vs_baseline_8thread"] = round(base_total / 8 / device_s, 2)
    persist_partial("host_baseline")

    # 4. AuPR parity: device-trained vs host-trained winner coefficients
    # scored on the SAME host data
    try:
        if "reg_param" in sweep["best_grid"] and remaining() > 120:
            delta, a_host, a_dev = aupr_parity(
                Xh, yh, masks_h, sweep["best_grid"], Xd, yd)
            RESULT["sweep"]["au_pr_host_fit"] = round(a_host, 4)
            RESULT["sweep"]["au_pr_device_fit"] = round(a_dev, 4)
            RESULT["sweep"]["au_pr_parity_delta"] = round(delta, 4)
    except Exception as e:
        errors.append(f"parity: {type(e).__name__}: {e}")
    persist_partial("aupr_parity")
    del Xh, Xd  # free 2 x [n, d] before the host-heavy phases

    # 5. wide transmogrify + example configs, in CPU children
    configs = {}
    RESULT["configs"] = configs
    try:
        if remaining() > 240:
            configs["wide_transmogrify"] = run_subprocess_phase(
                ["--wide", str(cfg["wide_rows"])],
                min(remaining() - 120, 600))
        else:
            errors.append("wide_transmogrify skipped: budget")
    except Exception as e:
        errors.append(f"wide: {type(e).__name__}: {str(e)[:200]}")
    persist_partial("wide_transmogrify")
    import tempfile
    cache_dir = tempfile.mkdtemp(prefix="bench_xla_cache_")
    for key, mod in (("titanic_s", "op_titanic_simple"),
                     ("iris_s", "op_iris"), ("boston_s", "op_boston")):
        try:
            if remaining() > 90:
                configs[key] = run_subprocess_phase(
                    ["--example", mod], min(remaining() - 40, 240),
                    compile_cache=cache_dir)["s"]
                log(f"{mod}: {configs[key]}s")
            else:
                errors.append(f"{mod} skipped: budget")
        except Exception as e:
            errors.append(f"{mod}: {type(e).__name__}: {str(e)[:200]}")
        persist_partial(f"example_{key}")
    # model-quality parity on the canonical real dataset (skipped when the
    # reference checkout is absent)
    try:
        if os.path.isfile(REF_TITANIC) and remaining() > 90:
            configs["titanic_quality"] = run_subprocess_phase(
                ["--quality"], min(remaining() - 40, 240),
                compile_cache=cache_dir)
            log(f"titanic quality: {configs['titanic_quality']}")
    except Exception as e:
        errors.append(f"titanic quality: {type(e).__name__}: {str(e)[:200]}")
    persist_partial("titanic_quality")
    # cold-vs-warm XLA-compile-cache effect: a SECOND cold process of the
    # same example pays tracing but loads compiles from the per-run cache
    # dir the first run just populated (a controlled pair — the user-level
    # cache is excluded from both)
    try:
        if "titanic_s" in configs and remaining() > 90:
            configs["titanic_s_cached_process"] = run_subprocess_phase(
                ["--example", "op_titanic_simple"],
                min(remaining() - 40, 240), compile_cache=cache_dir)["s"]
            log(f"titanic cached-process: "
                f"{configs['titanic_s_cached_process']}s")
    except Exception as e:
        errors.append(f"titanic warm: {type(e).__name__}: {str(e)[:200]}")
    persist_partial("example_warm")

    if trace_dir:
        from transmogrifai_tpu.utils.metrics import collector as _coll
        _coll.event("run_end", run_type="bench")
        save_trace_artifacts()
        # every traced bench run feeds the plan corpus (docs/planning.md)
        harvest_spans_to_corpus("bench_trace")
        _coll.detach_event_log()
        _coll.disable()
    if not errors:
        RESULT.pop("errors", None)
    signal.alarm(0)
    persist_partial("complete")
    print(json.dumps(RESULT), flush=True)


def _silence_broken_stdout():
    """Point stdout at devnull so the interpreter-shutdown flush of a
    broken pipe can't flip the exit status to 120 (python docs pattern)."""
    os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        _silence_broken_stdout()
        sys.exit(0)  # consumer closed stdout; nothing left to say
    except Exception as e:  # never exit without a parseable JSON line
        RESULT.setdefault("errors", []).append(
            f"{type(e).__name__}: {e}")
        persist_partial("fatal_error")
        save_trace_artifacts()
        try:
            print(json.dumps(RESULT), flush=True)
        except BrokenPipeError:
            _silence_broken_stdout()
        sys.exit(0)  # the error field conveys failure; keep rc green
